"""Benchmark: batched TPU scheduling vs the serial control path.

Reproduces the BASELINE.md synthetic stress config: a mixed fleet of
PropagationPolicy styles (Duplicated / StaticWeight / DynamicWeight /
Aggregated, with and without cluster spread constraints) over a large member
fleet, scheduled end to end (encode -> jitted solve -> decode), chunked so
device memory stays bounded.  The serial baseline runs the identical
scenario through ops/serial.schedule on a subsample and is extrapolated.

Prints ONE JSON line:
  {"metric": ..., "value": bindings/s (batched, end-to-end),
   "unit": "bindings/s", "vs_baseline": speedup vs serial path,
   ...detail fields...}
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import subprocess
import sys
import time

from typing import Dict, List, Optional

import numpy as np

# -- defensive backend bring-up ----------------------------------------------
# The TPU tunnel in this environment has been flaky across rounds: round 1
# saw a fast UNAVAILABLE crash at backend init, round 2 a jax.devices() hang.
# Importing jax is always fast; only backend *init* misbehaves.  So: probe
# the backend ONCE in a subprocess with a LONG budget (a hang cannot be
# interrupted in-process), then — if healthy — run the bench in THIS process
# against the same backend.  A persistent compilation cache (enabled below)
# makes the in-process warm-up cheap across runs.  When the probe fails the
# bench degrades to the fastest WORKING backend — the native C++ serial
# pipeline, ~13x faster than XLA:CPU batched on this workload — and the
# result is marked unmissably (metric prefixed CPU-FALLBACK, vs_baseline
# forced to 0): a number whose hardware silently changed is worse than no
# number, and a fallback slower than the serial loop it replaces is an
# operational bug (the probe/resolution policy is shared with
# `karmadactl serve` via karmada_tpu/utils/deviceprobe.py).

from karmada_tpu.utils.deviceprobe import probe_backend  # noqa: F401 (re-export: watch_bench.py uses bench.probe_backend)


def enable_persistent_compile_cache(platform_hint: str = "cpu") -> None:
    """Compile once per machine, not once per run (must precede first jit).

    Thin delegation to the ONE shared owner, ops/aotcache.enable(): the
    cache dir is keyed by platform, host CPU features and jax version
    there (XLA:CPU AOT artifacts are host-feature-specific — observed
    SIGILL loading a foreign artifact; accelerator executables target the
    CHIP and share one dir across hosts, so a chip window never re-pays
    the long solver compiles just because the host changed between
    rounds).  Arming also feeds the
    karmada_solver_compile_cache_{hits,misses}_total counters."""
    from karmada_tpu.ops import aotcache

    aotcache.enable(platform_hint=platform_hint)


def force_cpu_fallback() -> None:
    """Pin jax to the host CPU platform (jax may already be imported)."""
    from karmada_tpu.utils.jaxenv import force_cpu

    force_cpu()


def _device_topology() -> dict:
    """Devices THIS process ended up with (call only after the first jit
    already ran — jax.devices() on a virgin process could hang on a dead
    tunnel, which is exactly what the out-of-process probe exists for)."""
    try:
        import jax

        d = jax.devices()
        return {"devices": len(d), "platform": d[0].platform}
    except Exception:  # noqa: BLE001 — topology is advisory
        return {"devices": None, "platform": None}


def _mesh_info() -> dict:
    """Active solver-mesh snapshot (never initialises a backend)."""
    from karmada_tpu.ops import meshing

    return meshing.mesh_info()


# -- checkpointing -----------------------------------------------------------
# The tunnel drops mid-run (observed r3: the chip answered for ~2h windows
# and vanished mid-bench, losing everything).  The timed run therefore
# checkpoints per chunk to bench_ckpt/chunks.jsonl: a re-run with the same
# config + source digest + platform kind skips finished chunks and keeps
# their measurements, so a relay drop costs one chunk, not the run.  A
# completed TPU result is also persisted whole (tpu_latest.json) so the
# round-end bench can report the real measurement even if the chip is down
# at that exact moment (marked `cached` with its timestamp — an honest
# labelled measurement beats a CPU fallback number).

def _repo_dir() -> str:
    return os.path.dirname(os.path.abspath(__file__))


def default_ckpt_dir() -> str:
    return os.path.join(_repo_dir(), "bench_ckpt")


_SOLVER_SOURCES = ("karmada_tpu/ops/solver.py", "karmada_tpu/ops/tensors.py",
                   "karmada_tpu/ops/spread.py", "karmada_tpu/ops/serial.py",
                   "bench.py")
# serial-control cache key: the control's own code AND everything that
# shapes the synthetic workload it runs (a cached baseline measured on a
# different workload would silently corrupt the reported speedup)
_SERIAL_SOURCES = ("karmada_tpu/ops/serial.py",
                   "karmada_tpu/native/serial_solver.cc",
                   "karmada_tpu/estimator/general.py",
                   "bench.py")


def source_digest(sources=_SOLVER_SOURCES) -> str:
    """Digest of the named sources: chunks measured against different code
    must never be mixed into one aggregate."""
    import hashlib

    h = hashlib.sha1()
    for rel in sources:
        p = os.path.join(_repo_dir(), rel)
        try:
            with open(p, "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"?")
    return h.hexdigest()[:16]


def config_sig(args, platform_kind: str) -> str:
    return (f"b{args.bindings}-c{args.clusters}-k{args.chunk}"
            f"-w{args.waves}-{platform_kind}-{source_digest()}")


def load_ckpt(path: str, sig: str):
    """Return (done: {chunk_idx: record}, prior_elapsed_s).

    prior_elapsed_s sums, per earlier session, that session's span (max
    t_rel among its chunks) — the honest elapsed contribution of work
    already done.  Aggregate results are marked `resumed` downstream.
    The forward and rebalance passes checkpoint under distinct sigs into
    the same file."""
    done: Dict[int, dict] = {}
    sessions: Dict[str, float] = {}
    try:
        with open(path) as f:
            for ln in f:
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    continue  # torn final line from a killed run
                if rec.get("sig") != sig:
                    continue
                if rec.get("kind") == "fresh":
                    # --fresh generation marker: everything this sig
                    # recorded before it is retired
                    done.clear()
                    sessions.clear()
                    continue
                if rec.get("kind") == "rebalance" or "ci" not in rec:
                    # legacy pre-sig_reb bench versions logged the
                    # rebalance pass as kind="rebalance" records (ci=-1)
                    # under the FORWARD sig: folding them in would store a
                    # phantom done[-1] and inflate prior_elapsed, deflating
                    # the resumed throughput
                    continue
                ci = int(rec["ci"])
                if ci < 0:
                    continue  # same legacy class, defensively
                if ci in done:
                    # first-wins: a concurrent duplicate run of the same
                    # sig must not add its span to prior_elapsed twice
                    continue
                done[ci] = rec
                s = rec.get("session", "?")
                sessions[s] = max(sessions.get(s, 0.0), float(rec["t_rel"]))
    except OSError:
        pass
    return done, sum(sessions.values())


class ChunkLog:
    """Append-only per-chunk measurement log (one JSON line per chunk)."""

    def __init__(self, path: str, sig: str, prune: bool = False) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self.path, self.sig = path, sig
        import uuid

        self.session = uuid.uuid4().hex[:8]
        self.t0 = time.perf_counter()
        # advisory exclusive lock: two concurrent runs of the same config
        # (watcher + a manual run) interleaving chunk records would corrupt
        # the resume aggregation; the loser runs uncheckpointed
        self.disabled = False
        try:
            import fcntl

            # per-sig lock: concurrent runs of DIFFERENT configs are safe
            # (append-only single-line writes, load filters by sig); hash
            # the sig so near-identical sigs (forward vs "-reb" rebalance
            # pass) never truncate onto the same lock file
            import hashlib

            sig_tag = hashlib.sha1(sig.encode()).hexdigest()[:16]
            self._lockf = open(f"{path}.{sig_tag}.lock", "w")
            fcntl.flock(self._lockf, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self.disabled = True
            print("[bench] another bench holds the checkpoint lock; this "
                  "run will not checkpoint", file=sys.stderr, flush=True)
        if prune and not self.disabled:
            # --fresh: retire this sig's earlier records with an APPEND-ONLY
            # generation marker (load_ckpt discards same-sig records seen
            # before it).  A rewrite would race concurrent different-config
            # appenders, which the per-sig lock deliberately allows.
            self.append(kind="fresh")

    def reset_t0(self) -> None:
        """Start the session span at the TIMED run, not at warmup: t_rel
        reconstructs each session's elapsed contribution on resume."""
        self.t0 = time.perf_counter()

    def append(self, **rec) -> None:
        if self.disabled:
            return
        rec.update(sig=self.sig, session=self.session,
                   t_rel=round(time.perf_counter() - self.t0, 3))
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())


def _serial_cache_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, "serial_controls.json")


def load_serial_cache(ckpt_dir: str, key: str) -> Optional[dict]:
    try:
        with open(_serial_cache_path(ckpt_dir)) as f:
            return json.load(f).get(key)
    except (OSError, json.JSONDecodeError):
        return None


def save_serial_cache(ckpt_dir: str, key: str, rec: dict) -> None:
    os.makedirs(ckpt_dir, exist_ok=True)
    path = _serial_cache_path(ckpt_dir)
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        data = {}
    data[key] = rec
    with open(path, "w") as f:
        json.dump(data, f)


def _tpu_latest_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, "tpu_latest.json")


def load_tpu_latest(ckpt_dir: str, args) -> Optional[dict]:
    """A completed TPU measurement for THIS config (any source digest —
    the digest it ran against is recorded inside for the reader)."""
    try:
        with open(_tpu_latest_path(ckpt_dir)) as f:
            rec = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    cfg = rec.get("config", {})
    if (cfg.get("bindings") == args.bindings
            and cfg.get("clusters") == args.clusters
            and cfg.get("chunk") == args.chunk
            and cfg.get("waves") == args.waves
            and cfg.get("carry", False) == getattr(args, "carry", False)):
        return rec
    return None


def save_tpu_latest(ckpt_dir: str, args, payload: dict) -> None:
    os.makedirs(ckpt_dir, exist_ok=True)
    rec = {
        "config": {"bindings": args.bindings, "clusters": args.clusters,
                   "chunk": args.chunk, "waves": args.waves,
                   "carry": getattr(args, "carry", False)},
        "source_digest": source_digest(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "payload": payload,
    }
    with open(_tpu_latest_path(ckpt_dir), "w") as f:
        json.dump(rec, f)


def emit_cached_tpu(rec: dict, why_no_live: str) -> None:
    """Print a persisted TPU measurement as the round result, unmissably
    labelled as a cached (but real, on-chip) measurement."""
    payload = dict(rec["payload"])
    detail = dict(payload.get("detail", {}))
    detail.update(
        cached=True,
        measured_at=rec.get("measured_at"),
        cached_source_digest=rec.get("source_digest"),
        live_attempt=why_no_live,
    )
    payload["detail"] = detail
    payload["metric"] = payload["metric"] + " [cached on-TPU measurement]"
    print(json.dumps(payload))


# -- watchdog ----------------------------------------------------------------
# The probe bounds backend *init* hangs, but the tunnel can also stall
# MID-RUN (observed this round: probe ok in 0.2 s, then a dispatch blocked
# forever on the relay socket).  A hung XLA call cannot be interrupted
# in-process, so by default main() re-executes itself as an --inner child
# that emits heartbeat lines on stderr at every phase boundary and chunk.
# "Progress" is child output OR CPU time advancing anywhere in the child's
# process group (local XLA compiles are silent but burn CPU; a relay hang
# is silent AND idle).  The parent only intervenes after
# `--no-progress-timeout` seconds of neither, then falls back to a
# loudly-labelled CPU run recording why.

_HB_ON = False


def _hb(msg: str) -> None:
    if _HB_ON:
        print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _pgroup_cpu_s(pgid: int) -> float:
    """Total utime+stime (seconds) of every process in a process group —
    the probe runs as a grandchild, so walk /proc rather than just the
    child pid."""
    total = 0.0
    tick = os.sysconf("SC_CLK_TCK")
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/stat") as f:
                parts = f.read().rsplit(") ", 1)[-1].split()
            # fields after comm: state=0, ppid=1, pgrp=2, ..., utime=11, stime=12
            if int(parts[2]) == pgid:
                total += (int(parts[11]) + int(parts[12])) / tick
        except (OSError, IndexError, ValueError):
            continue  # raced with process exit
    return total


def _last_json_line(lines) -> Optional[str]:
    """Newest stdout line that parses as a JSON object — the ONE-line
    result contract (a SIGKILL can truncate a partially-flushed line)."""
    for ln in reversed(list(lines)):
        if ln.strip().startswith("{"):
            try:
                json.loads(ln)
                return ln
            except json.JSONDecodeError:
                continue
    return None


def run_with_watchdog(argv, no_progress_timeout: float,
                      cpu_fallback: bool = True) -> int:
    import threading

    cmd = [sys.executable, os.path.abspath(__file__), *argv, "--inner"]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, start_new_session=True,  # own process group: kill takes
    )                                       # hung XLA/relay threads with it
    last_progress = [time.monotonic()]
    stdout_lines: list = []

    def drain(stream, sink) -> None:
        for line in stream:
            last_progress[0] = time.monotonic()
            if sink is not None:
                sink.append(line)
            else:
                sys.stderr.write(line)
                sys.stderr.flush()

    threads = [
        threading.Thread(target=drain, args=(proc.stdout, stdout_lines), daemon=True),
        threading.Thread(target=drain, args=(proc.stderr, None), daemon=True),
    ]
    for t in threads:
        t.start()
    hung = False
    cpu_seen = 0.0
    while proc.poll() is None:
        cpu_now = _pgroup_cpu_s(proc.pid)
        # any change counts as progress: an increase is compile/solve work,
        # a DROP means a subprocess (e.g. the probe) exited — also activity,
        # and the baseline must follow it down or the child gets no CPU
        # credit until it re-exceeds the departed process's accrued time
        if abs(cpu_now - cpu_seen) > 0.5:
            cpu_seen = cpu_now
            last_progress[0] = time.monotonic()
        idle = time.monotonic() - last_progress[0]
        if idle > no_progress_timeout:
            hung = True
            import signal

            print(f"[bench] no output and no CPU for {idle:.0f}s: killing "
                  "the device attempt, falling back to CPU",
                  file=sys.stderr, flush=True)
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                proc.kill()
            break
        time.sleep(2.0)
    proc.wait()
    for t in threads:
        t.join(timeout=5.0)

    result_line = _last_json_line(stdout_lines)
    if result_line is not None:
        # even a killed child may have printed a completed result first
        # (hang during teardown) — a real measurement always wins
        sys.stdout.write(result_line)
        sys.stdout.flush()
        return 0 if hung else (proc.returncode or 0)

    # device attempt hung (or died without a result): CPU fallback, marked
    why = (f"device attempt hung ({no_progress_timeout:.0f}s without progress)"
           if hung else
           f"device attempt died rc={proc.returncode} without a result")
    if not cpu_fallback:
        # watcher mode: finished chunks are checkpointed; report and let
        # the caller retry when the device answers again
        print(json.dumps({"metric": "device attempt failed (no-cpu-fallback)",
                          "value": 0, "unit": "bindings/s", "vs_baseline": 0,
                          "detail": {"error": why}}))
        return 3
    fb = subprocess.run(
        [sys.executable, os.path.abspath(__file__), *argv,
         "--inner", "--force-cpu", "--prefer-cached"],
        stdout=subprocess.PIPE, text=True,
    )
    fb_line = _last_json_line((fb.stdout or "").splitlines())
    if fb_line is None:
        print(json.dumps({"metric": "bench failed", "value": 0,
                          "unit": "bindings/s", "vs_baseline": 0,
                          "detail": {"error": why,
                                     "fallback_rc": fb.returncode}}))
        return 1
    payload = json.loads(fb_line)  # pre-validated by _last_json_line
    payload.setdefault("detail", {})["tpu_attempt"] = why
    print(json.dumps(payload))
    return fb.returncode or 0

from karmada_tpu.estimator.general import GeneralEstimator
from karmada_tpu.models.cluster import (
    APIEnablement,
    Cluster,
    ClusterSpec,
    ClusterStatus,
    ResourceSummary,
)
from karmada_tpu.models.meta import ObjectMeta
from karmada_tpu.models.policy import (
    DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
    REPLICA_DIVISION_AGGREGATED,
    REPLICA_DIVISION_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_REGION,
    ClusterAffinity,
    ClusterPreferences,
    Placement,
    ReplicaSchedulingStrategy,
    SpreadConstraint,
)
from karmada_tpu.models.work import (
    ObjectReference,
    ReplicaRequirements,
    ResourceBinding,
    ResourceBindingSpec,
    ResourceBindingStatus,
)
from karmada_tpu.ops import serial, tensors
from karmada_tpu.utils.quantity import Quantity

GVK = ("apps/v1", "Deployment")


def build_fleet(rng: random.Random, n_clusters: int):
    clusters = []
    for i in range(n_clusters):
        clusters.append(
            Cluster(
                metadata=ObjectMeta(name=f"member-{i:05d}"),
                spec=ClusterSpec(region=f"r{i % 8}", provider=f"p{i % 3}"),
                status=ClusterStatus(
                    api_enablements=[APIEnablement(GVK[0], [GVK[1]])],
                    resource_summary=ResourceSummary(
                        allocatable={
                            "cpu": Quantity.from_milli(rng.randint(16000, 128000)),
                            "memory": Quantity.from_units(rng.randint(64, 512)),
                            "pods": Quantity.from_units(rng.randint(110, 256)),
                        },
                        allocated={
                            "cpu": Quantity.from_milli(rng.randint(0, 8000)),
                            "memory": Quantity.from_units(rng.randint(0, 32)),
                            "pods": Quantity.from_units(rng.randint(0, 40)),
                        },
                    ),
                ),
            )
        )
    return clusters


def build_placements(rng: random.Random, names):
    """The BASELINE.md config mix; affinity subsets keep fan-out realistic."""
    placements = []

    def subset_affinity():
        k = rng.randint(3, min(24, len(names)))
        start = rng.randrange(len(names))
        picked = [names[(start + j) % len(names)] for j in range(k)]
        return ClusterAffinity(cluster_names=picked)

    for _ in range(8):  # Duplicated across an affinity subset
        placements.append(Placement(
            cluster_affinity=subset_affinity(),
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED),
        ))
    for _ in range(8):  # StaticWeight split
        placements.append(Placement(
            cluster_affinity=subset_affinity(),
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            ),
        ))
    for _ in range(8):  # DynamicWeight over the whole fleet
        placements.append(Placement(
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            ),
        ))
    for _ in range(8):  # Aggregated with a cluster spread constraint
        placements.append(Placement(
            spread_constraints=[SpreadConstraint(
                spread_by_field=SPREAD_BY_FIELD_CLUSTER, min_groups=2, max_groups=6)],
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_AGGREGATED,
            ),
        ))
    for _ in range(8):  # region topology spread (device group math + host DFS)
        rmin = rng.randint(1, 2)
        placements.append(Placement(
            spread_constraints=[
                SpreadConstraint(
                    spread_by_field=SPREAD_BY_FIELD_REGION,
                    min_groups=rmin, max_groups=rng.randint(rmin, 3),
                ),
                SpreadConstraint(
                    spread_by_field=SPREAD_BY_FIELD_CLUSTER,
                    min_groups=2, max_groups=6,
                ),
            ],
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS),
            ),
        ))
    return placements


def build_bindings(rng: random.Random, n_bindings: int, placements):
    items = []
    for b in range(n_bindings):
        spec = ResourceBindingSpec(
            resource=ObjectReference(
                api_version=GVK[0], kind=GVK[1], namespace=f"ns-{b % 64}",
                name=f"app-{b}", uid=f"uid-{b}",
            ),
            replicas=rng.choice([1, 2, 3, 5, 10, 20, 50]),
            replica_requirements=ReplicaRequirements(resource_request={
                "cpu": Quantity.from_milli(rng.choice([100, 250, 500])),
                "memory": Quantity.from_units(rng.choice([1, 2, 4])),
            }),
            placement=placements[b % len(placements)],
        )
        items.append((spec, ResourceBindingStatus()))
    return items


def run_batched(items, cindex, estimator, chunk: int, cache=None, waves: int = 8,
                ckpt_done=None, ckpt_log=None, carry: bool = False):
    """Returns (elapsed_s, solve_s, scheduled_count, chunk_lat, chunk_wall):
    chunk_lat is each chunk's OWN work (encode span + finalize span);
    chunk_wall is its submit-to-results wall time, which under pipelining
    also contains the interleaved work of neighboring chunks.

    The loop itself lives in scheduler/pipeline.run_pipeline — the SAME
    pipelined chunk executor scheduler/service._solve_device drives, so
    the benchmarked path IS the production path: chunk k's device solve
    dispatches asynchronously while the host finalizes chunk k-1 and
    encodes chunk k+1, against one shared EncoderCache, with `waves`-deep
    capacity contention per chunk.

    ckpt_done ({chunk_idx: record}) skips chunks a previous session already
    measured, folding their stored counts/latencies into the aggregates;
    ckpt_log (ChunkLog) records each newly finalized chunk.  Both optional
    — the warmup and XLA:CPU-comparison callers leave them off; the timed
    forward and rebalance passes each thread their own (distinct sigs).

    carry=True threads the consumed-capacity accumulators chunk to chunk
    (solver carry-in/out): the main solve of chunk k+1 prices against
    everything chunks <=k consumed — sequential-equivalent accounting at
    chunk granularity.  The carry chains DEVICE-SIDE (the executor feeds
    chunk k's live used-out arrays as chunk k+1's used0 operands, and
    pending spread/big contributions fold in as lazy device adds), so on
    the steady vocabulary the pipeline stays overlapped instead of
    serializing; the sub-solves' consumption reaches the chain at the
    next dispatch boundary (one-chunk lag).  Incompatible with checkpoint
    resume (a skipped chunk's consumption would be lost).
    """
    from karmada_tpu.scheduler import pipeline as sched_pipeline

    assert not (carry and ckpt_done), \
        "--carry is incompatible with checkpoint resume"
    n = len(items)
    n_chunks = (n + chunk - 1) // chunk
    cache = cache if cache is not None else tensors.EncoderCache()
    scheduled = 0
    failures: Dict[str, int] = {}
    solve_s = 0.0
    chunk_lat = []   # per-chunk own work: encode span + finalize span
    chunk_wall = []  # submit -> results wall time (includes pipeline overlap)
    done = ckpt_done or {}
    for ci in range(n_chunks):
        rec = done.get(ci)
        if rec is None:
            continue
        scheduled += int(rec["scheduled"])
        for k, v in rec.get("failures", {}).items():
            failures[k] = failures.get(k, 0) + int(v)
        chunk_lat.append(float(rec["lat"]))
        chunk_wall.append(float(rec["wall"]))
        solve_s += float(rec.get("solve_s", 0.0))
        _hb(f"chunk {ci + 1} restored from checkpoint")

    def on_chunk(st) -> None:
        nonlocal scheduled, solve_s
        scheduled += st.n_ok
        for k, v in st.failures.items():
            failures[k] = failures.get(k, 0) + v
        chunk_lat.append(st.own_s)
        chunk_wall.append(st.wall_s)
        solve_s += st.solve_s
        if ckpt_log is not None:
            ckpt_log.append(ci=st.index, n=st.n, scheduled=st.n_ok,
                            failures=st.failures, lat=round(st.own_s, 4),
                            wall=round(st.wall_s, 4),
                            solve_s=round(st.solve_s, 4))
        # telemetry plane: one ring sample per finalized chunk when the
        # bench armed the sampler (disarmed cost is one global read) —
        # the direct-pipeline bench has no scheduler cycle hook, so the
        # chunk boundary is its cycle clock
        from karmada_tpu.obs import timeseries as obs_ts

        obs_ts.maybe_sample(time.perf_counter())
        _hb(f"chunk {st.index + 1} finalized ({st.n} bindings)")

    t0 = time.perf_counter()
    sched_pipeline.run_pipeline(
        items, cindex, estimator, chunk=chunk, waves=waves, cache=cache,
        carry=carry, carry_spread=carry,
        skip=(None if not done else lambda ci: ci in done),
        on_chunk=on_chunk,
        # the bench aggregates counts only: holding 100k result lists (and
        # re-deriving FitError diagnosis per failed row) is pure overhead
        collect=False, diagnose=False,
    )
    return (time.perf_counter() - t0, solve_s, scheduled, chunk_lat,
            chunk_wall, failures)


def measure_explain_overhead(items, cindex, estimator, chunk: int,
                             waves: int):
    """Armed-vs-disarmed explain-plane cost on a bounded workload slice.

    Three timed pipeline runs (each pre-warmed): disarmed baseline, armed
    (explain jit variant + decision decode), disarmed again.  The armed
    delta is the explain plane's honest price; the second disarmed run
    PROVES arming did not pollute the disarmed path — it must trigger
    ZERO new jit compilations (asserted: compile state is exact where
    wall time is noisy) and its wall delta is reported for the payload.
    """
    from karmada_tpu.obs import decisions as dec
    from karmada_tpu.ops import solver
    from karmada_tpu.scheduler import pipeline as sched_pipeline

    sub = items[: min(len(items), 2 * chunk)]

    def one(rec):
        cache = tensors.EncoderCache()
        t0 = time.perf_counter()
        sched_pipeline.run_pipeline(
            sub, cindex, estimator, chunk=chunk, waves=waves, cache=cache,
            carry=False, explain=rec, collect=False, diagnose=False)
        return time.perf_counter() - t0

    one(None)  # warm the disarmed jit signatures
    t_dis = one(None)
    one(dec.DecisionRecorder(capacity=64))  # warm the armed variant
    t_armed = one(dec.DecisionRecorder(capacity=64))
    c_before = solver._jit_cache_size()  # noqa: SLF001
    t_dis2 = one(None)
    c_after = solver._jit_cache_size()  # noqa: SLF001
    new_compiles = (None if c_before is None or c_after is None
                    else c_after - c_before)
    assert new_compiles in (0, None), (
        f"disarmed pipeline compiled {new_compiles} new jit variant(s) "
        "after an explain-armed run — the disarmed path must stay "
        "byte-identical")
    pct = lambda a, b: round((a - b) / b * 100, 2) if b > 0 else None
    return {
        "explain_overhead_pct": pct(t_armed, t_dis),
        "explain_disarmed_delta_pct": pct(t_dis2, t_dis),
        # None (jax exposes no cache counter) is reported as null — a
        # consumer must be able to tell "verified 0" from "unmeasurable"
        "explain_disarmed_new_compiles": new_compiles,
    }


def arm_telemetry(capacity: int = 4096, deadline_s: float = 1.0):
    """Arm the telemetry plane (obs/timeseries + obs/slo) for a bench
    leg: an unthrottled ring sampled on whatever clock the measured
    path's cycles run on (the scheduler hook passes the queue clock —
    the soak's VirtualClock in compressed mode), plus the stock SLO
    objectives at the <1s-p99 north-star bound.  Returns the ring."""
    from karmada_tpu.obs import slo as obs_slo
    from karmada_tpu.obs import timeseries as obs_ts

    ring = obs_ts.configure(capacity=capacity, min_interval_s=0.0)
    # no regression watchdog here: bench legs run compressed virtual
    # time on host backends, where bindings/s is the ServiceModel's
    # axis, not the hardware's — the envelope comparison belongs to
    # live serve (--telemetry)
    obs_slo.configure(objectives=obs_slo.default_objectives(
        schedule_deadline_s=deadline_s), arm_watchdog=False)
    return ring


def disarm_telemetry() -> None:
    from karmada_tpu.obs import timeseries as obs_ts

    obs_ts.disarm()  # also disarms the SLO evaluator


def measure_sampler_overhead(reference_cycle_s, samples: int = 64) -> dict:
    """The telemetry sampler's honest price: time `samples` forced ring
    snapshots of the LIVE registry (post-run, so the families carry the
    run's full label population) against a reference cycle cost, and
    prove the sampler is pure host bookkeeping — zero new jit
    compilations and zero new metric families (asserted, explain-plane
    style: state is exact where wall time is noisy)."""
    from karmada_tpu.obs import timeseries as obs_ts
    from karmada_tpu.ops import solver
    from karmada_tpu.utils.metrics import REGISTRY

    ring = obs_ts.MetricRing(capacity=samples + 1)
    c_before = solver._jit_cache_size()  # noqa: SLF001
    fams_before = len(REGISTRY.snapshot())
    ring.sample(0.0, force=True)  # warm (allocator, family iteration)
    t0 = time.perf_counter()
    for i in range(samples):
        ring.sample(float(i + 1), force=True)
    per_sample_s = (time.perf_counter() - t0) / samples
    c_after = solver._jit_cache_size()  # noqa: SLF001
    fams_after = len(REGISTRY.snapshot())
    new_compiles = (None if c_before is None or c_after is None
                    else c_after - c_before)
    assert new_compiles in (0, None), (
        f"the telemetry sampler triggered {new_compiles} jit "
        "compilation(s) — sampling must be pure host bookkeeping")
    # the sampler's own counters pre-exist; sampling must never mint
    # metric families of its own (the zero-new-metric-cost contract)
    assert fams_after == fams_before, (
        f"sampling grew the registry {fams_before} -> {fams_after} "
        "families")
    overhead_pct = (round(per_sample_s / reference_cycle_s * 100, 3)
                    if reference_cycle_s and reference_cycle_s > 0 else None)
    return {
        "sampler_per_sample_ms": round(per_sample_s * 1e3, 4),
        "sampler_overhead_pct": overhead_pct,
        "sampler_new_compiles": new_compiles,
        "sampler_reference_cycle_ms": (
            round(reference_cycle_s * 1e3, 4) if reference_cycle_s else None),
        "registry_families": fams_after,
    }


def measure_disarmed_overhead(reference_cycle_s, iters: int = 20000) -> dict:
    """The DISARMED telemetry hook's price — the acceptance gate: the
    default serve cycle pays one module-global read at the sample site,
    which must stay under 1% of a cycle and trigger zero jit compiles
    (asserted by --slo, explain-plane style)."""
    from karmada_tpu.obs import timeseries as obs_ts
    from karmada_tpu.ops import solver

    assert obs_ts.active() is None, \
        "disarmed-cost measurement needs the sampler disarmed"
    c_before = solver._jit_cache_size()  # noqa: SLF001
    t0 = time.perf_counter()
    for i in range(iters):
        obs_ts.maybe_sample(float(i))
    per_call_s = (time.perf_counter() - t0) / iters
    c_after = solver._jit_cache_size()  # noqa: SLF001
    new_compiles = (None if c_before is None or c_after is None
                    else c_after - c_before)
    return {
        "disarmed_per_call_us": round(per_call_s * 1e6, 4),
        "disarmed_overhead_pct": (
            round(per_call_s / reference_cycle_s * 100, 5)
            if reference_cycle_s and reference_cycle_s > 0 else None),
        "disarmed_new_compiles": new_compiles,
    }


def measure_ledger_overhead(reference_cycle_s, iters: int = 20000) -> dict:
    """The lifecycle ledger's honest price — the --slo acceptance gate:
    the ARMED per-event record (timed on a private ledger in its two
    shapes: the coalescing tail bump a steady stream of identical events
    takes, and the fresh-event path rotating refs take) and the DISARMED
    module-emitter no-op (one global list read), each against a mean
    scheduling cycle.  Pure host bookkeeping — zero jit compiles
    (asserted, explain-plane style)."""
    from karmada_tpu.obs import events as obs_events
    from karmada_tpu.ops import solver

    c_before = solver._jit_cache_size()  # noqa: SLF001
    led = obs_events.EventLedger(capacity=4096)
    ref = obs_events.ObjectRef("ResourceBinding", "bench", "ledger")
    led.record(ref, obs_events.TYPE_NORMAL,
               obs_events.REASON_BINDING_ENQUEUED, "enqueued")  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        led.record(ref, obs_events.TYPE_NORMAL,
                   obs_events.REASON_BINDING_ENQUEUED, "enqueued")
    coalesce_s = (time.perf_counter() - t0) / iters
    refs = [obs_events.ObjectRef("ResourceBinding", "bench", f"l{i}")
            for i in range(1024)]
    t0 = time.perf_counter()
    for i in range(iters):
        led.record(refs[i & 1023], obs_events.TYPE_NORMAL,
                   obs_events.REASON_SCHEDULE_BINDING_SUCCEED,
                   f"scheduled round {i >> 10}")
    fresh_s = (time.perf_counter() - t0) / iters
    was_armed = obs_events.armed()
    obs_events.disarm()
    try:
        t0 = time.perf_counter()
        for _ in range(iters):
            obs_events.emit_key(("bench", "ledger"), obs_events.TYPE_NORMAL,
                                obs_events.REASON_BINDING_ENQUEUED,
                                "enqueued")
        disarmed_s = (time.perf_counter() - t0) / iters
    finally:
        if was_armed:
            obs_events.arm()
    c_after = solver._jit_cache_size()  # noqa: SLF001
    new_compiles = (None if c_before is None or c_after is None
                    else c_after - c_before)
    armed_s = max(coalesce_s, fresh_s)
    pct = lambda s: (round(s / reference_cycle_s * 100, 5)
                     if reference_cycle_s and reference_cycle_s > 0 else None)
    return {
        "ledger_armed_per_event_us": round(armed_s * 1e6, 4),
        "ledger_coalesce_per_event_us": round(coalesce_s * 1e6, 4),
        "ledger_armed_overhead_pct": pct(armed_s),
        "ledger_disarmed_per_call_us": round(disarmed_s * 1e6, 4),
        "ledger_disarmed_overhead_pct": pct(disarmed_s),
        "ledger_new_compiles": new_compiles,
    }


def measure_lock_overhead(reference_cycle_s, iters: int = 20000) -> dict:
    """The runtime race detector's honest price — the concurrency-vet
    acceptance gate: a DISARMED VetLock enter/exit (one arming-flag list
    read plus delegation to the wrapped stdlib lock) and the ARMED
    bookkeeping path (thread-local stack + ownership + hold-time
    histogram), each per-op and against a mean scheduling cycle.  The
    disarmed path must also register ZERO new metric families (all three
    karmada_lock_* families register at import) and zero jit compiles —
    both asserted here, explain-plane style."""
    from karmada_tpu.analysis import guards
    from karmada_tpu.ops import solver
    from karmada_tpu.utils import locks as locks_mod
    from karmada_tpu.utils.metrics import REGISTRY

    c_before = solver._jit_cache_size()  # noqa: SLF001
    fam_before = len(REGISTRY.snapshot())
    lock = locks_mod.VetLock("bench.lock-overhead")
    was_armed = guards.armed()
    guards.arm(False)
    try:
        with lock:
            pass  # warm
        t0 = time.perf_counter()
        for _ in range(iters):
            with lock:
                pass
        disarmed_s = (time.perf_counter() - t0) / iters
        guards.arm(True)
        with lock:
            pass  # warm the armed path (thread-local stack init)
        t0 = time.perf_counter()
        for _ in range(iters):
            with lock:
                pass
        armed_s = (time.perf_counter() - t0) / iters
    finally:
        guards.arm(was_armed)
    fam_after = len(REGISTRY.snapshot())
    assert fam_after == fam_before, (
        f"VetLock traffic registered {fam_after - fam_before} new metric "
        "families; the karmada_lock_* families must register at import")
    c_after = solver._jit_cache_size()  # noqa: SLF001
    new_compiles = (None if c_before is None or c_after is None
                    else c_after - c_before)
    pct = lambda s: (round(s / reference_cycle_s * 100, 5)
                     if reference_cycle_s and reference_cycle_s > 0 else None)
    return {
        "lock_disarmed_per_op_us": round(disarmed_s * 1e6, 4),
        "lock_disarmed_overhead_pct": pct(disarmed_s),
        "lock_armed_per_op_us": round(armed_s * 1e6, 4),
        "lock_armed_overhead_pct": pct(armed_s),
        "lock_new_metric_families": fam_after - fam_before,
        "lock_new_compiles": new_compiles,
    }


def measure_flight_overhead(reference_cycle_s, iters: int = 20000) -> dict:
    """The incident plane's honest price — the --slo acceptance gate:
    the ARMED per-cycle flight record (a representative cycle-shaped
    dict appended to a private ring) and the DISARMED module hook (one
    global list read), each against a mean scheduling cycle.  Pure host
    bookkeeping — zero jit compiles (asserted, ledger-plane style)."""
    from karmada_tpu.obs import incidents as obs_incidents
    from karmada_tpu.ops import solver

    c_before = solver._jit_cache_size()  # noqa: SLF001
    rec = obs_incidents.FlightRecorder(capacity=512)

    def one(i):
        rec.record({"kind": "cycle", "t": float(i), "cycle_id": i,
                    "trace_id": None, "popped": 32, "batch": 32,
                    "cut": "window", "backend": "device",
                    "degraded_from": None, "overload": False,
                    "fault": None, "scheduled": 32, "unschedulable": 0,
                    "errors": 0, "elapsed_s": 0.01, "dwell_max_s": 0.02,
                    "pipeline": None, "shortlist": None,
                    "depths": {"active": 0, "backoff": 0},
                    "oldest_s": {"active": 0.0}})

    one(0)  # warm
    t0 = time.perf_counter()
    for i in range(iters):
        one(i)
    armed_s = (time.perf_counter() - t0) / iters
    was_armed = obs_incidents.flight_armed()
    obs_incidents.arm_flight(False)
    try:
        t0 = time.perf_counter()
        for i in range(iters):
            obs_incidents.record("cycle", cycle_id=i)
        disarmed_s = (time.perf_counter() - t0) / iters
    finally:
        obs_incidents.arm_flight(was_armed)
    c_after = solver._jit_cache_size()  # noqa: SLF001
    new_compiles = (None if c_before is None or c_after is None
                    else c_after - c_before)
    pct = lambda s: (round(s / reference_cycle_s * 100, 5)
                     if reference_cycle_s and reference_cycle_s > 0 else None)
    return {
        "flight_armed_per_record_us": round(armed_s * 1e6, 4),
        "flight_armed_overhead_pct": pct(armed_s),
        "flight_disarmed_per_call_us": round(disarmed_s * 1e6, 4),
        "flight_disarmed_overhead_pct": pct(disarmed_s),
        "flight_new_compiles": new_compiles,
    }


def build_rebalance_items(rng: random.Random, items, names):
    """BASELINE config 5's second half: bindings that WERE scheduled now
    need re-assignment (descheduler marks clusters lossy / triggers
    reschedule). Prev assignments seed Steady scale-up/down and Fresh
    paths — the exact solver modes the descheduler reuses."""
    import dataclasses

    from karmada_tpu.models.work import TargetCluster

    out = []
    for k, (spec, status) in enumerate(items):
        prev_n = rng.randint(1, 4)
        start = rng.randrange(len(names))
        per = max(1, spec.replicas // prev_n)
        prev = [
            TargetCluster(name=names[(start + j) % len(names)], replicas=per)
            for j in range(prev_n)
        ]
        new_spec = dataclasses.replace(
            spec,
            clusters=prev,
            # a third of the fleet gets an explicit reschedule trigger
            # (WorkloadRebalancer / failover path -> Fresh mode)
            reschedule_triggered_at=(100.0 if k % 3 == 0 else None),
        )
        out.append((new_spec, ResourceBindingStatus()))
    return out


def run_serial(items, clusters, estimator):
    cal = serial.make_cal_available([estimator])
    t0 = time.perf_counter()
    n_ok = 0
    for spec, status in items:
        try:
            serial.schedule(spec, status, clusters, cal)
            n_ok += 1
        except Exception:  # noqa: BLE001
            pass
    return time.perf_counter() - t0, n_ok


def run_serial_native(items, clusters):
    """The honest Go-equivalent control: the C++ serial scheduler
    (karmada_tpu/native/serial_solver.cc, golden-tested against
    ops/serial.schedule).  Marshaling runs outside the timed region — it is
    input prep, the analog of the reference reading informer caches.
    Returns (elapsed_s, n_bindings) or None when the toolchain is absent."""
    from karmada_tpu import native

    if not native.available():
        return None
    snap = native.NativeSnapshot(clusters, native.collect_res_names(items))
    nb = native.marshal_batch(items, snap)
    t0 = time.perf_counter()
    results = native.run_marshaled(nb, snap)
    elapsed = time.perf_counter() - t0
    n_ok = sum(1 for st, _ in results if st == native.STATUS_OK)
    return elapsed, n_ok


def _run_native_chunked(items, clusters, chunk: int, cal):
    """Run the full scenario through the native C++ backend in
    `chunk`-sized slices (same granularity as the device path, so the
    p99 numbers are comparable).  Marshaling is input prep (the analog of
    the reference reading informer caches / the device path's untimed
    H2D) and is reported separately; the timed region is the solve.
    Bindings the native pipeline marks UNSUPPORTED fall through to the
    Python serial path exactly like scheduler/service.py, timed.

    Returns (solve_s, marshal_s, ok, failures, chunk_lat)."""
    from karmada_tpu import native as native_mod

    snap = native_mod.NativeSnapshot(
        clusters, native_mod.collect_res_names(items))
    solve_s = marshal_s = 0.0
    ok = 0
    failures: Dict[str, int] = {}
    chunk_lat = []
    for lo in range(0, len(items), chunk):
        part = items[lo : lo + chunk]
        t0 = time.perf_counter()
        nb = native_mod.marshal_batch(part, snap)
        t1 = time.perf_counter()
        results = native_mod.run_marshaled(nb, snap)
        unsupported = [i for i, (st, _) in enumerate(results)
                       if st == native_mod.STATUS_UNSUPPORTED]
        for i in unsupported:
            spec, status = part[i]
            try:
                serial.schedule(spec, status, clusters, cal)
                results[i] = (native_mod.STATUS_OK, None)
            except Exception as e:  # noqa: BLE001 — per-binding failure class
                failures[type(e).__name__] = (
                    failures.get(type(e).__name__, 0) + 1)
                results[i] = (-1, None)
        t2 = time.perf_counter()
        marshal_s += t1 - t0
        solve_s += t2 - t1
        chunk_lat.append(t2 - t1)
        for st, _ in results:
            if st == native_mod.STATUS_OK:
                ok += 1
            elif st == native_mod.STATUS_UNSCHEDULABLE:
                failures["UnschedulableError"] = (
                    failures.get("UnschedulableError", 0) + 1)
            elif st == native_mod.STATUS_FIT_ERROR:
                failures["FitError"] = failures.get("FitError", 0) + 1
            elif st == native_mod.STATUS_NO_CLUSTER:
                failures["NoClusterAvailableError"] = (
                    failures.get("NoClusterAvailableError", 0) + 1)
        _hb(f"native chunk {lo // chunk + 1} done")
    return solve_s, marshal_s, ok, failures, chunk_lat


def measure_serial_controls(args, items, clusters, estimator) -> dict:
    """Measure (or restore from cache) the serial control throughputs —
    platform-independent pure host CPU work, measured once per config and
    never allowed to spend a chip window.  Single authority for BOTH the
    device bench and the native fallback (a drifted copy once mislabelled
    a Python-speed control as the C++ Go-equivalent baseline)."""
    serial_key = (f"b{args.bindings}-c{args.clusters}"
                  f"-s{args.serial_sample}-{source_digest(_SERIAL_SOURCES)}")
    cached = (None if args.fresh
              else load_serial_cache(args.ckpt_dir, serial_key))
    if cached is not None:
        _hb("serial controls restored from cache")
        return dict(cached, cached=True)
    _hb("serial controls starting")
    # prefer the C++ control (Go-equivalent); it is fast enough to run a
    # much larger sample than the Python port
    native_sample = items[
        :: max(1, len(items) // (args.serial_sample * 32))][
        : args.serial_sample * 32]
    nat = run_serial_native(native_sample, clusters)
    sample = items[:: max(1, len(items) // args.serial_sample)][
        : args.serial_sample]
    serial_elapsed, _ = run_serial(sample, clusters, estimator)
    py_serial_bps = (len(sample) / serial_elapsed
                     if serial_elapsed > 0 else 0.0)
    native_ok = nat is not None and nat[0] > 0
    if native_ok:
        serial_bps = len(native_sample) / nat[0]
        serial_lang = "c++ -O2 (native Go-equivalent control)"
    else:
        serial_bps = py_serial_bps
        serial_lang = ("python (Go-port control; Go itself would be "
                       "~10-100x faster)")
    rec = {
        "serial_bps": serial_bps, "py_serial_bps": py_serial_bps,
        "serial_lang": serial_lang, "native_ok": native_ok,
        "native_sample": len(native_sample) if native_ok else len(sample),
        "py_sample": len(sample),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    save_serial_cache(args.ckpt_dir, serial_key, rec)
    return dict(rec, cached=False)


def run_native_fallback(args, rng, clusters, items, estimator, cindex,
                        probe, platform) -> None:
    """The no-accelerator bench path: measure the native C++ backend over
    the FULL config (forward + rebalance), plus an XLA:CPU batched
    subsample for comparison.  The headline is the fastest backend actually
    available — `serve --backend device` degrades to native the same way
    (utils/deviceprobe.resolve_backend), so this is what a production
    deployment would really run on this host."""
    cal = serial.make_cal_available([estimator])
    _hb("native fallback: forward pass starting")
    solve_s, marshal_s, ok, failures, chunk_lat = _run_native_chunked(
        items, clusters, args.chunk, cal)
    throughput = len(items) / solve_s if solve_s > 0 else 0.0
    _hb(f"native fallback forward done: {throughput:.1f} bindings/s")

    # descheduler rebalance loop (BASELINE config 5, second half) over ALL
    # bindings — prev seats seed Steady scale-up/down and Fresh paths
    reb_items = build_rebalance_items(rng, items, [c.name for c in clusters])
    reb_solve_s, _, reb_ok, _, reb_lat = _run_native_chunked(
        reb_items, clusters, args.chunk, cal)
    reb_bps = len(reb_items) / reb_solve_s if reb_solve_s > 0 else 0.0
    _hb(f"native fallback rebalance done: {reb_bps:.1f} bindings/s")

    # XLA:CPU batched comparison subsample (the device program on host):
    # reported so the reroute decision stays auditable round over round
    xla_bps = None
    xla_stage_timeline = None
    n_xla = min(args.xla_cpu_sample, len(items))
    if n_xla > 0:
        from karmada_tpu import obs
        from karmada_tpu.obs.export import latest_pipeline_timeline

        cache = tensors.EncoderCache()
        sample = items[:n_xla]
        run_batched(sample[: args.chunk], cindex, estimator, args.chunk,
                    cache, waves=args.waves)  # compile warmup
        tail = n_xla % args.chunk
        if tail:
            run_batched(sample[:tail], cindex, estimator, args.chunk,
                        cache, waves=args.waves)
        obs.TRACER.configure(capacity=2, slow_keep=0)
        xla_elapsed, _, _, _, _, _ = run_batched(
            sample, cindex, estimator, args.chunk, cache, waves=args.waves)
        xla_stage_timeline = latest_pipeline_timeline(obs.TRACER.recorder)
        obs.TRACER.disable()
        xla_bps = n_xla / xla_elapsed if xla_elapsed > 0 else 0.0
        _hb(f"XLA:CPU comparison sample done: {xla_bps:.1f} bindings/s")

    # serial controls (cached off-window like the device path)
    sc = measure_serial_controls(args, items, clusters, estimator)
    serial_bps = sc["serial_bps"]
    speedup = throughput / serial_bps if serial_bps > 0 else 0.0
    payload = {
        "metric": (f"CPU-FALLBACK (NOT TPU; native C++ backend) scheduled "
                   f"bindings/sec, {args.bindings} bindings x "
                   f"{args.clusters} clusters"),
        "value": round(throughput, 1),
        "unit": "bindings/s",
        "vs_baseline": 0,  # not a TPU measurement, never reported as one
        "detail": {
            "platform": platform,
            "mesh": _mesh_info(),
            "fallback_backend": "native",
            # the operational invariant VERDICT r4 demanded: the fallback
            # must be at least as fast as the serial control it replaces
            "cpu_fallback_speedup": round(speedup, 2),
            "xla_cpu_batched_bps": (round(xla_bps, 1)
                                    if xla_bps is not None else None),
            "xla_cpu_sample": n_xla,
            # stage attribution for the XLA path (the device program's
            # stages exist even on host CPU; native has no such pipeline)
            "xla_stage_timeline": xla_stage_timeline,
            "backend_probe": probe,
            "batched_solve_s": round(solve_s, 3),
            "marshal_s": round(marshal_s, 3),
            "p99_chunk_latency_s": round(
                float(np.percentile(chunk_lat, 99)), 4) if chunk_lat else None,
            "scheduled_ok": ok,
            "failed_by_class": failures,
            "rebalance_bindings_per_s": round(reb_bps, 1),
            "rebalance_ok": reb_ok,
            "rebalance_p99_chunk_s": round(
                float(np.percentile(reb_lat, 99)), 4) if reb_lat else None,
            "serial_bindings_per_s": round(serial_bps, 2),
            "serial_python_bindings_per_s": round(sc["py_serial_bps"], 2),
            "serial_sample": sc["native_sample"],
            "serial_python_sample": sc["py_sample"],
            "serial_cached": sc["cached"],
            "chunk": args.chunk,
            "waves": args.waves,
            "serial_lang": sc["serial_lang"],
        },
    }
    print(json.dumps(payload))


def _targets_of(res_map):
    """Comparable rendering of a PipelineResult.results map: exception
    class name for failures, {cluster: replicas} for schedules."""
    out = {}
    for i, r in res_map.items():
        out[i] = (type(r).__name__ if isinstance(r, Exception)
                  else {t.name: t.replicas for t in r})
    return out


def run_mesh_bench(args, shape) -> int:
    """--mesh mode: the same workload through scheduler/pipeline twice —
    single-device, then sharded over a (bindings, clusters) mesh — with a
    bit-identical parity check and a topology + 1-vs-N timing payload.
    `shape` is main()'s already-parsed --mesh value: "auto" or a (B, C)
    tuple (main runs the regular bench when it parses to None).

    Always pins virtual CPU devices BEFORE backend init (the mode
    validates that the mesh-sharded production path compiles, executes and
    matches, and must never block on a dead accelerator tunnel).  On this
    platform the collectives are thread rendezvous on shared host cores,
    so the speedup tracks spare cores, not ICI (docs/PERF_NOTES.md); the
    topology + parity fields are the signal, the on-chip run reuses the
    identical code path.
    """
    from karmada_tpu.ops import meshing
    from karmada_tpu.utils.jaxenv import force_cpu

    n_dev = (max(2, args.mesh_devices) if shape == "auto"
             else shape[0] * shape[1])
    pinned = force_cpu(n_dev)
    import jax

    enable_persistent_compile_cache("cpu")
    devs = jax.devices()
    if len(devs) < n_dev:
        print(json.dumps({
            "metric": "mesh bench failed (devices)", "value": 0,
            "unit": "bindings/s", "vs_baseline": 0,
            "detail": {"error": f"need {n_dev} devices, have {len(devs)}"
                       + ("" if pinned else
                          " (jax initialised before the virtual-device "
                          "pin; run bench.py --mesh in a fresh process)")},
        }))
        return 1
    if shape == "auto":
        shape = meshing.default_shape(n_dev)
    _hb(f"mesh bench: {shape[0]}x{shape[1]} over {n_dev} virtual "
        f"{devs[0].platform} devices")

    from karmada_tpu.scheduler import pipeline as sched_pipeline

    rng = random.Random(0)
    clusters = build_fleet(rng, args.mesh_clusters)
    placements = build_placements(rng, [c.name for c in clusters])
    items = build_bindings(rng, args.mesh_bindings, placements)
    estimator = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    chunk, waves = args.mesh_chunk, args.waves

    def leg(label):
        """Warm the jit signatures, then time the full workload (carry on:
        the chunk-to-chunk device-resident carry chain is exactly what
        must survive sharding)."""
        cache = tensors.EncoderCache()
        sched_pipeline.run_pipeline(
            items[:min(chunk, len(items))], cindex, estimator, chunk=chunk,
            waves=waves, cache=cache, carry=True, carry_spread=True)
        tail = len(items) % chunk
        if tail:
            sched_pipeline.run_pipeline(
                items[:tail], cindex, estimator, chunk=chunk, waves=waves,
                cache=cache, carry=True, carry_spread=True)
        _hb(f"mesh bench: {label} warmup done; timing")
        cache.reset_for_cycle()
        t0 = time.perf_counter()
        res = sched_pipeline.run_pipeline(
            items, cindex, estimator, chunk=chunk, waves=waves, cache=cache,
            carry=True, carry_spread=True)
        elapsed = time.perf_counter() - t0
        _hb(f"mesh bench: {label} timed leg done in {elapsed:.1f}s "
            f"({res.scheduled} scheduled)")
        return elapsed, res

    try:
        meshing.deactivate()
        single_s, single_res = leg("single-device")
        plan = meshing.activate(shape, devices=devs)
        assert plan is not None
        info = meshing.mesh_info()
        sharded_s, sharded_res = leg(f"sharded {plan.shape_str}")
    finally:
        meshing.deactivate()

    want, got = _targets_of(single_res.results), _targets_of(
        sharded_res.results)
    mismatches = sorted(
        i for i in set(want) | set(got) if want.get(i) != got.get(i))
    n = len(items)
    payload = {
        "metric": (f"mesh bench: sharded ({info['shape']}) vs "
                   f"single-device compact solve, {n} bindings x "
                   f"{args.mesh_clusters} clusters"),
        "value": round(n / sharded_s, 1) if sharded_s > 0 else 0,
        "unit": "bindings/s",
        "vs_baseline": 0,  # never a TPU headline: virtual CPU topology run
        "detail": {
            "mesh": info,
            "platform": devs[0].platform,
            "devices": len(devs),
            "single_device_s": round(single_s, 3),
            "sharded_s": round(sharded_s, 3),
            "mesh_speedup": (round(single_s / sharded_s, 3)
                             if sharded_s > 0 else None),
            "single_device_bps": (round(n / single_s, 1)
                                  if single_s > 0 else 0),
            "parity_ok": not mismatches,
            "parity_mismatches": mismatches[:16],
            "scheduled_ok": sharded_res.scheduled,
            "failed_by_class": sharded_res.failures,
            "bindings": n, "clusters": args.mesh_clusters,
            "chunk": chunk, "waves": waves,
            "note": ("virtual CPU mesh: collectives are thread rendezvous "
                     "on host cores, so mesh_speedup tracks the host's "
                     "spare cores (< 1 on a one-core box), not ICI; "
                     "parity + topology are the signal "
                     "(docs/PERF_NOTES.md 'Mesh sharding')"),
        },
    }
    if mismatches:
        payload["metric"] = "MESH PARITY FAILED: " + payload["metric"]
        payload["value"] = 0
    print(json.dumps(payload))
    return 1 if mismatches else 0


def run_delta_bench(args) -> int:
    """--delta mode: steady-state cycle timing with the resident-state
    plane (karmada_tpu/resident) against today's full re-encode path,
    with the fused device-gather path (ops/resident_gather) measured ON
    and OFF side by side.

    The full leg re-encodes and re-solves the WHOLE fleet through
    scheduler/pipeline (what every cycle cost before the resident plane).
    The resident legs model the watch-driven steady state: the plane has
    adopted every binding's encoded row, then each cycle a churn fraction
    of bindings (rv bump + replica change) and clusters (capacity delta)
    arrives and ONLY the churned bindings are scheduled — cached rows
    gather, misses re-encode, cluster columns advance by the delta apply.
    Each resident leg runs twice, against a host-assemble control state
    and a fused state whose binding rows gather on device.

    The warm RE-PLACE leg is the fusion headline: capacity-only cluster
    churn re-prices the fleet, so the whole fleet re-schedules with every
    row a cache HIT — the cycle where encode assembly was the remaining
    host wall.  Each timed cycle carries a per-stage host-budget
    breakdown (encode-assembly / gather / dispatch / d2h / decode ms,
    from the scheduler step-latency histograms) and the binding-axis
    h2d transfer counter, so the fused payoff is a committed number:
    host_ms (encode+gather+dispatch+d2h+decode) per cycle, fused vs
    host, plus the asserted ZERO binding-field uploads on the fused
    path (karmada_solver_h2d_binding_fields_total).

    Parity is asserted four ways: the timed churn cycles' re-encoded row
    counts must equal the churned-binding counts exactly, every churned
    subset is re-scheduled through the plain full-encode path and the
    placements compared, the fused and host-control placements must
    match on every leg, and each plane ends with its own bit-exact audit
    (compare_batches over a from-scratch re-encode of the whole fleet).
    Host-only guarantee: forces XLA:CPU before backend init (the
    resident path is the device backend's code, byte-identical on the
    CPU fallback) — never blocks on the tunnel.
    """
    force_cpu_fallback()
    enable_persistent_compile_cache("cpu")
    import copy

    from karmada_tpu.ops.solver import H2D_BINDING_FIELDS
    from karmada_tpu.resident import ResidentState, RowToken
    from karmada_tpu.scheduler import metrics as sm
    from karmada_tpu.scheduler import pipeline as sched_pipeline

    try:
        churn_levels = [float(x) for x in args.delta_churn.split(",") if x]
        assert churn_levels and all(0 < f <= 1 for f in churn_levels)
    except (ValueError, AssertionError):
        print(json.dumps({"metric": "delta bench failed (churn levels)",
                          "value": 0, "unit": "bindings/s", "vs_baseline": 0,
                          "detail": {"error": f"bad --delta-churn "
                                              f"{args.delta_churn!r}"}}))
        return 1

    n, nc = args.bindings, args.clusters
    chunk, waves = args.chunk, args.waves
    rng = random.Random(0)
    clusters = build_fleet(rng, nc)
    placements = build_placements(rng, [c.name for c in clusters])
    items = build_bindings(rng, n, placements)
    estimator = GeneralEstimator()
    rvs = [1] * n  # the bench's resourceVersion ledger (bumped on churn)

    import jax

    platform = jax.devices()[0].platform
    _hb(f"delta bench: {n} bindings x {nc} clusters on {platform} "
        f"(chunk {chunk}, churn {churn_levels}, fused on+off)")

    # -- per-stage host-budget accounting ------------------------------------
    _STAGES = (("encode", sm.STEP_ENCODE), ("dispatch", sm.STEP_H2D),
               ("solve_wait", sm.STEP_SOLVE), ("d2h", sm.STEP_D2H),
               ("decode", sm.STEP_DECODE))

    def _snap(state):
        return ({k: sm.STEP_LATENCY.sum(schedule_step=s)
                 for k, s in _STAGES},
                state.stats()["fused"]["gather_s"],
                H2D_BINDING_FIELDS.value())

    def _breakdown(before, state, cycle_s):
        stages0, g0, h0 = before
        stages1, g1, h1 = _snap(state)
        gather_ms = (g1 - g0) * 1e3
        ms = {k: round((stages1[k] - stages0[k]) * 1e3, 2) for k, _ in _STAGES}
        # the gather dispatch rides inside the encode hook's span: split
        # it out so "encode_assembly" is the pure host assembly cost
        out = {
            "encode_assembly_ms": round(ms["encode"] - gather_ms, 2),
            "gather_ms": round(gather_ms, 2),
            "dispatch_ms": ms["dispatch"],
            "solve_wait_ms": ms["solve_wait"],
            "d2h_ms": ms["d2h"],
            "decode_ms": ms["decode"],
            "host_ms": round(ms["encode"] + ms["dispatch"] + ms["d2h"]
                             + ms["decode"], 2),
            "cycle_ms": round(cycle_s * 1e3, 1),
            "h2d_binding_fields": int(h1 - h0),
        }
        return out

    def full_cycle(sub):
        """Today's path: full re-encode + solve of `sub` (fresh caches)."""
        return sched_pipeline.run_pipeline(
            sub, tensors.ClusterIndex.build(clusters), estimator,
            chunk=chunk, waves=waves, cache=tensors.EncoderCache(),
            carry=True, carry_spread=True)

    # -- full-re-encode leg (the r05 baseline path) --------------------------
    full_cycle(items[:min(chunk, len(items))])  # warm the chunk signature
    tail = len(items) % chunk
    if tail:
        full_cycle(items[:tail])
    _hb("delta bench: full-leg warmup done; timing full re-encode cycle")
    t0 = time.perf_counter()
    full_res = full_cycle(items)
    full_s = time.perf_counter() - t0
    full_bps = n / full_s if full_s > 0 else 0.0
    _hb(f"delta bench: full re-encode cycle {full_s:.1f}s "
        f"({full_bps:.1f} bindings/s, {full_res.scheduled} scheduled)")

    # -- resident planes: host-assemble control + fused gather ---------------
    states = {
        "host": ResidentState(estimator=estimator, audit_interval=0),
        "fused": ResidentState(estimator=estimator, audit_interval=0,
                               fused=True),
    }

    def tokens(mode, idx):
        return [RowToken(f"bench-{mode}/{i}", rvs[i]) for i in idx]

    def resident_cycle(mode, idx):
        """One watch-driven steady-state cycle: delta apply + schedule of
        exactly `idx` against the mode's resident plane."""
        state = states[mode]
        state.begin_cycle(clusters)
        toks = tokens(mode, idx)
        sub = [items[i] for i in idx]

        def encode(part, offset, armed):
            return state.encode_cycle(
                part, toks[offset:offset + len(part)], explain=armed)

        return sched_pipeline.run_pipeline(
            sub, state.cindex, estimator, chunk=chunk, waves=waves,
            cache=state.enc_cache, carry=True, carry_spread=True,
            encode=encode)

    for mode, state in states.items():
        state.begin_cycle(clusters)
        state.encode_cycle(items, tokens(mode, range(n)))  # adopt
    _hb(f"delta bench: resident planes adopted {len(states['host'].rows)} "
        f"rows each (host + fused)")

    def churn_bindings(idx):
        for i in idx:
            spec, status = items[i]
            items[i] = (dataclasses.replace(spec, replicas=spec.replicas + 1),
                        status)
            rvs[i] += 1

    def churn_clusters(k):
        """Capacity deltas on k clusters (fresh objects, like a store
        snapshot): the resident rv sweep must scatter these columns."""
        for lane in rng.sample(range(nc), k):
            c = copy.deepcopy(clusters[lane])
            c.metadata.resource_version += 1
            rs = c.status.resource_summary
            if rs is not None and "cpu" in rs.allocated:
                rs.allocated["cpu"] = Quantity.from_milli(
                    rs.allocated["cpu"].milli_value() + 100)
            clusters[lane] = c

    runs = []
    exact = True
    fused_h2d_clean = True
    for frac in churn_levels:
        k = max(1, int(n * frac))
        # warm this cycle size's jit signatures on CHURNED size-k cycles
        # (the timed cycle must not self-warm): random size-k subsets,
        # themselves churned, so the miss re-encode, the fused slot-row
        # scatter (pow2 lane bucket of k), the gather (pow2 B of k) and
        # the spread/big sub-solve buckets all compile before timing; a
        # cluster churn first warms the delta-apply scatter bucket too.
        # TWO rounds: the first fused cycle after a rebuild re-places the
        # whole slot store (no scatter), so only the second round's
        # misses reach — and warm — the scatter kernels.
        for _ in range(2):
            churn_clusters(max(1, int(nc * frac)))
            warm_idx = sorted(rng.sample(range(n), k))
            churn_bindings(warm_idx)
            for mode in states:
                resident_cycle(mode, warm_idx)
        # TWO timed rounds, keep each mode's BETTER (min host_ms) round:
        # the first can absorb a one-off jit compile for a route
        # composition the warm subsets never produced, and the decode
        # stage occasionally stalls behind the next chunk's in-flight
        # solve (stochastic, hits either mode) — the per-mode minimum is
        # the noise-floor host budget.  Re-encode exactness is asserted
        # every round; the final round's placements are parity-checked
        # against the full path and across modes.
        modes = {}
        mode_targets = {}
        for _round in range(2):
            churned = sorted(rng.sample(range(n), k))
            churn_bindings(churned)
            churn_clusters(max(1, int(nc * frac)))
            prev_modes = modes
            modes = {}
            mode_targets = {}
            for mode, state in states.items():
                h0, m0 = state.hits, state.misses
                before = _snap(state)
                t0 = time.perf_counter()
                res = resident_cycle(mode, churned)
                dt = time.perf_counter() - t0
                hits, misses = state.hits - h0, state.misses - m0
                exact = exact and misses == k and hits == 0
                steady = n / dt if dt > 0 else 0.0
                modes[mode] = {
                    "cycle_s": round(dt, 4),
                    "steady_bps": round(steady, 1),
                    "churned_bps": round(k / dt, 1) if dt > 0 else 0.0,
                    "hits": hits, "misses": misses,
                    "reencode_exact": misses == k,
                    "speedup_vs_full": (round(full_s / dt, 2) if dt > 0
                                        else None),
                    "stages": _breakdown(before, state, dt),
                }
                mode_targets[mode] = _targets_of(res.results)
            for mode, rec in prev_modes.items():
                if rec["stages"]["host_ms"] < \
                        modes[mode]["stages"]["host_ms"]:
                    modes[mode] = rec
        # parity: the same churned subset through the full-encode path,
        # and fused-vs-host on every binding
        want = _targets_of(full_cycle([items[i] for i in churned]).results)
        mism = sorted(
            i for i in set(want) | set(mode_targets["host"])
            | set(mode_targets["fused"])
            if not (want.get(i) == mode_targets["host"].get(i)
                    == mode_targets["fused"].get(i)))
        runs.append({
            "churn_frac": frac, "churned": k,
            "modes": modes,
            "parity_ok": not mism, "parity_mismatches": mism[:16],
        })
        _hb(f"delta bench: {frac:.0%} churn — host "
            f"{modes['host']['cycle_s'] * 1e3:.0f}ms / fused "
            f"{modes['fused']['cycle_s'] * 1e3:.0f}ms "
            f"(host-budget {modes['host']['stages']['host_ms']:.0f} -> "
            f"{modes['fused']['stages']['host_ms']:.0f}ms, "
            f"parity {'ok' if not mism else 'FAILED'})")

    # -- warm re-place leg: capacity churn, whole fleet, every row a HIT -----
    # This is the fusion headline: with no binding churn the cycle's host
    # work is exactly the per-cycle assembly + transfer + decode — the
    # wall the fused gather removes.
    churn_clusters(max(1, nc // 100))
    for mode in states:
        resident_cycle(mode, range(n))  # warm the all-hits signatures
    replace_modes = {}
    replace_targets = {}
    for _round in range(2):  # per-mode min host_ms round (see churn legs)
        churn_clusters(max(1, nc // 100))
        prev_modes = replace_modes
        replace_modes = {}
        replace_targets = {}
        for mode, state in states.items():
            h0, m0 = state.hits, state.misses
            before = _snap(state)
            t0 = time.perf_counter()
            res = resident_cycle(mode, range(n))
            dt = time.perf_counter() - t0
            replace_modes[mode] = {
                "cycle_s": round(dt, 4),
                "replace_bps": round(n / dt, 1) if dt > 0 else 0.0,
                "hits": state.hits - h0, "misses": state.misses - m0,
                "stages": _breakdown(before, state, dt),
            }
            replace_targets[mode] = _targets_of(res.results)
        for mode, rec in prev_modes.items():
            if rec["stages"]["host_ms"] < \
                    replace_modes[mode]["stages"]["host_ms"]:
                replace_modes[mode] = rec
    if replace_modes["fused"]["stages"]["h2d_binding_fields"] != 0:
        fused_h2d_clean = False
    replace_mism = sorted(
        i for i in set(replace_targets["host"]) | set(replace_targets["fused"])
        if replace_targets["host"].get(i) != replace_targets["fused"].get(i))
    host_budget = replace_modes["host"]["stages"]["host_ms"]
    fused_budget = replace_modes["fused"]["stages"]["host_ms"]
    budget_ratio = (round(host_budget / fused_budget, 2)
                    if fused_budget > 0 else None)
    # the acceptance comparison: the host share of a warm fused cycle,
    # per binding kept placed, against BENCH_r06's steady-state cycle
    # cost per binding (r06's 1%-churn cycle — whose wall was the
    # host<->device boundary this PR removes plus the solve).  Read from
    # the committed BENCH_r06.json when present.
    fused_host_us_per_binding = (fused_budget * 1e3 / n) if n else None
    r06_ref = None
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_r06.json")) as f:
            r06 = json.load(f)["detail"]["delta"]
        leg = r06["churn"][0]
        r06_us = leg["cycle_s"] * 1e6 / r06["bindings"]
        r06_ref = {
            "bindings": r06["bindings"], "clusters": r06["clusters"],
            "churn_frac": leg["churn_frac"],
            "steady_cycle_us_per_binding": round(r06_us, 2),
            "fused_warm_host_us_per_binding":
                round(fused_host_us_per_binding, 2),
            "host_time_vs_r06_steady_ratio":
                (round(r06_us / fused_host_us_per_binding, 1)
                 if fused_host_us_per_binding else None),
        }
    # vet: ignore[exception-hygiene] r06 reference is optional context; absence reported as null
    except Exception:  # noqa: BLE001 — no committed r06 on this checkout
        r06_ref = None
    replace = {
        "note": ("whole-fleet re-place on capacity-only churn: every row "
                 "a cache hit; host_ms is the per-cycle host budget "
                 "(encode-assembly + gather dispatch + solver dispatch + "
                 "d2h + decode) the fusion targets"),
        "modes": replace_modes,
        "parity_ok": not replace_mism,
        "parity_mismatches": replace_mism[:16],
        "host_budget_ms": {"host": host_budget, "fused": fused_budget},
        "host_budget_ratio": budget_ratio,
        "vs_r06_steady": r06_ref,
    }
    _hb(f"delta bench: re-place leg host-budget {host_budget:.0f}ms -> "
        f"{fused_budget:.0f}ms ({budget_ratio}x), fused binding-field "
        f"h2d {replace_modes['fused']['stages']['h2d_binding_fields']}, "
        f"vs r06 steady {r06_ref['host_time_vs_r06_steady_ratio'] if r06_ref else 'n/a'}x")

    # -- closing bit-exact audits over the whole fleet -----------------------
    audit_green = True
    stats_by_mode = {}
    for mode, state in states.items():
        state.begin_cycle(clusters)
        state.encode_cycle(items, tokens(mode, range(n)), audit=True)
        stats = state.stats()
        stats_by_mode[mode] = stats
        audit_green = audit_green and (stats["audits"]["mismatch"] == 0
                                       and stats["audits"]["ok"] >= 1)
    fused_stats = stats_by_mode["fused"]
    _hb(f"delta bench: closing audits {audit_green}; fused plane "
        f"{fused_stats['fused']}")

    # correctness verdict (parity_ok) and the hardware-dependent r06
    # performance gate are SEPARATE: a correct-but-slow run on a
    # throttled box must not read as a parity failure
    parity_ok = (all(r["parity_ok"] for r in runs) and exact
                 and replace["parity_ok"] and audit_green
                 and fused_h2d_clean
                 and fused_stats["fused"]["cycles"] > 0
                 and fused_stats["fused"]["fallbacks"] == {})
    r06_3x_ok = (r06_ref is None
                 or (r06_ref["host_time_vs_r06_steady_ratio"] or 0) >= 3.0)
    acceptance_ok = parity_ok and r06_3x_ok
    head = runs[0]["modes"]["fused"]
    payload = {
        "metric": (f"delta bench: fused resident steady-state "
                   f"({runs[0]['churn_frac']:.0%} churn) vs full "
                   f"re-encode, {n} bindings x {nc} clusters"),
        "value": head["steady_bps"] if acceptance_ok else 0,
        "unit": "bindings/s",
        "vs_baseline": 0,  # never a TPU headline: XLA:CPU host run
        "detail": {
            "delta": {
                "platform": platform,
                "bindings": n, "clusters": nc,
                "chunk": chunk, "waves": waves,
                "full_cycle_s": round(full_s, 3),
                "full_bps": round(full_bps, 1),
                "churn": runs,
                "replace": replace,
                "reencode_exact": exact,
                "audit_green": audit_green,
                "fused_h2d_clean": fused_h2d_clean,
                "parity_ok": parity_ok,
                "r06_3x_ok": r06_3x_ok,
                "acceptance_ok": acceptance_ok,
                "resident": fused_stats,
                "resident_host": stats_by_mode["host"],
                "note": ("steady_bps = fleet size / resident cycle wall: "
                         "the rate one plane keeps n bindings placed when "
                         "only the churned fraction re-enters the queue; "
                         "stages are the per-cycle host-budget breakdown "
                         "(docs/PERF_NOTES.md 'Whole-cycle-on-device')"),
            },
        },
    }
    if not parity_ok:
        payload["metric"] = "DELTA PARITY FAILED: " + payload["metric"]
    elif not acceptance_ok:
        payload["metric"] = ("DELTA HOST-BUDGET GATE MISSED (<3x vs r06): "
                             + payload["metric"])
    os.makedirs(args.ckpt_dir, exist_ok=True)
    with open(os.path.join(args.ckpt_dir, "delta_bench.json"), "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps(payload))
    return 0 if acceptance_ok else 1


def calibrate_service_model(backend: str = "serial", n: int = 128):
    """Measure the REAL per-binding / per-cycle cost of one batched
    scheduling cycle on this host+backend (wall clock, store writes
    included — the serve path's true cost), returning the loadgen
    ServiceModel the soak runs against.  With a measured model, a
    scenario's "2x capacity" arrival rate is 2x this host's measured
    solve throughput — the acceptance bar's overload condition."""
    from karmada_tpu.loadgen import ServeSlice, ServiceModel, VirtualClock
    from karmada_tpu.loadgen.driver import build_binding
    from karmada_tpu.loadgen.scenarios import get_scenario
    from karmada_tpu.models.cluster import Cluster

    scenario = get_scenario("steady")  # fleet shape only; traffic unused
    slice_ = ServeSlice(scenario, VirtualClock(), ServiceModel(),
                        backend=backend)
    clusters = list(slice_.store.list(Cluster.KIND))
    sched = slice_.scheduler

    def timed(count: int) -> float:
        bindings = [build_binding(f"calib-{count}-{i}")
                    for i in range(count)]
        for rb in bindings:
            slice_.store.create(rb)
        # drain the enqueued cycle work so the timed call is pure
        slice_.runtime.pump()
        t0 = time.perf_counter()
        sched.schedule_batch(bindings, clusters)
        return time.perf_counter() - t0

    timed(8)  # warm the path (imports, first-call caches)
    t_one = timed(1)
    t_n = timed(n)
    per_binding = max((t_n - t_one) / (n - 1), 1e-6)
    per_cycle = max(t_one - per_binding, 1e-6)
    return ServiceModel(per_binding_s=per_binding, per_cycle_s=per_cycle)


def run_soak(args) -> int:
    """bench --soak SCENARIO: calibrate the service model against this
    host's real solve cost, run the named loadgen scenario in compressed
    virtual time, and emit the SOAK payload (ONE JSON line, detail.soak;
    also persisted to <ckpt-dir>/soak_<scenario>.json)."""
    from karmada_tpu.loadgen import (
        LoadDriver, ServeSlice, VirtualClock, get_scenario,
    )

    try:
        scenario = get_scenario(args.soak)
    except ValueError as e:
        print(json.dumps({"metric": "soak failed (scenario)", "value": 0,
                          "unit": "s", "vs_baseline": 0,
                          "detail": {"error": str(e)}}))
        return 1
    _hb(f"soak {scenario.name}: calibrating service model "
        f"(backend={args.soak_backend})")
    model = calibrate_service_model(args.soak_backend)
    _hb(f"calibrated: per_binding={model.per_binding_s * 1e3:.3f}ms "
        f"per_cycle={model.per_cycle_s * 1e3:.3f}ms "
        f"(capacity ~{model.capacity_rate:.0f} bindings/s)")
    clock = VirtualClock()
    plane = ServeSlice(scenario, clock, model, backend=args.soak_backend)
    driver = LoadDriver(plane, scenario, clock=clock, model=model,
                        seed=args.soak_seed)
    # telemetry plane: the ring samples on the scheduler's cycle hook,
    # which in compressed mode runs on the soak's VirtualClock — the
    # series and the burn-rate windows are in virtual time.  The SOAK
    # payload embeds the verdict (loadgen/report.py reads the armed
    # evaluator), so every soak renders an SLO verdict.
    ring = arm_telemetry()
    try:
        payload = driver.run()
        # the sampler's price against the soak's own MEAN cycle cost
        # (one sample lands per cycle, so per-cycle is the honest
        # denominator; the raw per-sample ms rides along)
        mean_batch = ((payload.get("cycles") or {}).get("batch_size")
                      or {}).get("mean") or 1.0
        ref_cycle_s = model.cost(max(1.0, mean_batch))
        telemetry = measure_sampler_overhead(ref_cycle_s)
        telemetry["ring_samples"] = len(ring)
    finally:
        disarm_telemetry()
    telemetry.update(measure_disarmed_overhead(ref_cycle_s))
    telemetry.update(measure_ledger_overhead(ref_cycle_s))
    telemetry.update(measure_lock_overhead(ref_cycle_s))
    telemetry.update(measure_flight_overhead(ref_cycle_s))
    payload["backend"] = args.soak_backend
    payload["telemetry"] = telemetry
    if args.slo:
        # the acceptance gate (--slo): a real verdict from a real series,
        # and a disarmed path the serve cycle can ignore — burn rates
        # over >= 20 ring samples, the disarmed hook under 1% of a
        # cycle, zero compiles either way (the armed sampler's absolute
        # cost is reported above, not gated)
        slo_payload = payload.get("slo") or {}
        n_samples = (slo_payload.get("window") or {}).get("samples", 0)
        assert n_samples >= 20, (
            f"SLO verdict computed from only {n_samples} ring sample(s); "
            "the burn-rate windows need a real series (>= 20)")
        assert any(o.get("burn_rate", {}).get("long") is not None
                   for o in slo_payload.get("objectives", [])), (
            "no objective produced a burn-rate value over the soak window")
        assert telemetry["disarmed_overhead_pct"] is not None and \
            telemetry["disarmed_overhead_pct"] < 1.0, (
            f"disarmed telemetry hook costs "
            f"{telemetry['disarmed_overhead_pct']}% of a cycle — the "
            "disarmed serve path must be free (< 1%)")
        assert telemetry["disarmed_new_compiles"] in (0, None), (
            "the disarmed telemetry hook triggered jit compilation")
        # the lifecycle ledger's acceptance leg: recording an event (the
        # worst of the coalescing and fresh-event shapes) and the
        # disarmed emitter must each stay under 1% of a mean cycle, and
        # neither may touch the jit cache
        assert telemetry["ledger_armed_overhead_pct"] is not None and \
            telemetry["ledger_armed_overhead_pct"] < 1.0, (
            f"armed ledger record costs "
            f"{telemetry['ledger_armed_overhead_pct']}% of a cycle — the "
            "event journal must be noise (< 1%)")
        assert telemetry["ledger_disarmed_overhead_pct"] is not None and \
            telemetry["ledger_disarmed_overhead_pct"] < 1.0, (
            f"disarmed ledger emitter costs "
            f"{telemetry['ledger_disarmed_overhead_pct']}% of a cycle")
        assert telemetry["ledger_new_compiles"] in (0, None), (
            "the lifecycle ledger triggered jit compilation")
        # the concurrency-vet acceptance leg: a disarmed VetLock
        # enter/exit must be free (< 1% of a mean cycle), register no
        # new metric families, and never touch the jit cache
        assert telemetry["lock_disarmed_overhead_pct"] is not None and \
            telemetry["lock_disarmed_overhead_pct"] < 1.0, (
            f"disarmed VetLock enter/exit costs "
            f"{telemetry['lock_disarmed_overhead_pct']}% of a cycle — "
            "the disarmed serve path must be free (< 1%)")
        assert telemetry["lock_new_metric_families"] == 0, (
            "VetLock traffic registered new metric families")
        assert telemetry["lock_new_compiles"] in (0, None), (
            "the lock detector triggered jit compilation")
        # the incident plane's acceptance leg: an armed per-cycle flight
        # record and the disarmed hook must each stay under 1% of a
        # mean cycle, and neither may touch the jit cache
        assert telemetry["flight_armed_overhead_pct"] is not None and \
            telemetry["flight_armed_overhead_pct"] < 1.0, (
            f"armed flight record costs "
            f"{telemetry['flight_armed_overhead_pct']}% of a cycle — "
            "the flight ring must be noise (< 1%)")
        assert telemetry["flight_disarmed_overhead_pct"] is not None and \
            telemetry["flight_disarmed_overhead_pct"] < 1.0, (
            f"disarmed flight hook costs "
            f"{telemetry['flight_disarmed_overhead_pct']}% of a cycle")
        assert telemetry["flight_new_compiles"] in (0, None), (
            "the flight recorder triggered jit compilation")
        ledger_stats = payload.get("events") or {}
        assert ledger_stats.get("recorded", 0) > 0, (
            "the soak recorded zero lifecycle events — the ledger was "
            "disarmed or the emitters are dead")
    _hb(f"soak done: injected={payload['injected']} "
        f"scheduled={payload['scheduled']} "
        f"admission={payload['admission']} "
        f"slo_healthy={(payload.get('slo') or {}).get('healthy')}")
    os.makedirs(args.ckpt_dir, exist_ok=True)
    out_path = os.path.join(args.ckpt_dir, f"soak_{scenario.name}.json")
    with open(out_path, "w") as f:
        json.dump(payload, f, indent=2)
    p99 = payload["schedule_latency_s"].get("p99", 0.0)
    print(json.dumps({
        "metric": f"soak {scenario.name}: p99 schedule latency "
                  f"({payload['injected']} bindings, "
                  f"{scenario.load_factor:g}x capacity mean arrival)",
        "value": p99,
        "unit": "s",
        "vs_baseline": 0,
        "detail": {"soak": payload, "soak_path": out_path},
    }))
    return 0


def run_chaos(args) -> int:
    """bench --chaos SCENARIO: run a chaos-enabled loadgen scenario in
    compressed virtual time against the FULL hardened shape — device
    backend (XLA:CPU off-hardware), resident plane, mid-serve death
    guard with cooldown recovery, the estimator fan-out harness — and
    emit the CHAOS payload: the SOAK report plus the fault ledger and
    the safety auditor's conservation/accountability/recovery proof
    (ONE JSON line, detail.chaos; persisted to
    <ckpt-dir>/chaos_<scenario>.json — the CHAOS_r*.json contract)."""
    from karmada_tpu.loadgen import (
        LoadDriver,
        ServeSlice,
        ServiceModel,
        VirtualClock,
        get_scenario,
        warm_device_path,
    )

    try:
        scenario = get_scenario(args.chaos)
        if not scenario.chaotic:
            raise ValueError(
                f"scenario {scenario.name!r} schedules no chaos fault "
                "events; use --soak for fault-free scenarios")
    except ValueError as e:
        print(json.dumps({"metric": "chaos soak failed (scenario)",
                          "value": 0, "unit": "violations",
                          "vs_baseline": 0, "detail": {"error": str(e)}}))
        return 1
    _hb(f"chaos {scenario.name}: fixed service model, backend=device "
        "(XLA:CPU off-hardware), resident plane + death guard armed")
    # a FIXED model (not calibrated): the chaos payload's value is the
    # auditor verdict, not throughput, and fixing it keeps every fault's
    # virtual timing — and therefore the whole run — reproducible
    model = ServiceModel()
    clock = VirtualClock()
    plane = ServeSlice(scenario, clock, model, backend="device",
                       resident=True, resident_audit_interval=0,
                       device_cycle_timeout_s=2.0,
                       device_recover_cycles=2)
    # compile-warm OUTSIDE the death guard's window: the 2s guard must
    # measure stuck cycles, not the first call's jit compile
    warm_device_path(plane)
    driver = LoadDriver(plane, scenario, clock=clock, model=model,
                        seed=args.soak_seed)
    arm_telemetry()
    try:
        payload = driver.run()
    finally:
        disarm_telemetry()
    payload["backend"] = "device"
    audit = payload.get("safety_audit") or {}
    violations = audit.get("violations", [])
    _hb(f"chaos done: injected={payload['injected']} "
        f"scheduled={payload['scheduled']} "
        f"faults={audit.get('fault_fires')} "
        f"violations={len(violations)}")
    os.makedirs(args.ckpt_dir, exist_ok=True)
    out_path = os.path.join(args.ckpt_dir, f"chaos_{scenario.name}.json")
    with open(out_path, "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps({
        "metric": f"chaos {scenario.name}: safety-audit violations "
                  f"({payload['injected']} bindings, "
                  f"{sum((audit.get('fault_fires') or {}).values())} "
                  "faults fired)",
        "value": len(violations),
        "unit": "violations",
        "vs_baseline": 0,
        "detail": {"chaos": payload, "chaos_path": out_path},
    }))
    return 0 if not violations else 1


def run_facade(args) -> int:
    """bench --facade: the economic argument for the scheduler-as-a-
    service seam (karmada_tpu/facade) in one measured payload — many
    independent AssignReplicas callers coalesced server-side into few
    device dispatches vs the same callers served one dispatch each:

      * serial control: a window=1 FacadeService (every call is its own
        detached solve — the per-call RPC estimator shape), timed over
        --facade-serial-sample sequential calls;
      * coalesced leg: a window=--facade-window service with
        --facade-callers calls in flight at once (assign_async — the
        event-driven server-admission shape, so the measurement prices
        the SERVICE, not synthetic caller threads fighting for the
        GIL); one detached solve per formed batch, per-call demux.
        Speedup = serial per-call time / coalesced per-call time — the
        batch former must deliver >= 50x (the padded device dispatch
        costs nearly the same whether it carries 1 binding or a full
        window);
      * what-if isolation proof: live placements snapshotted before and
        after a placement/cluster-loss/headroom query burst must be
        bit-identical (the COW-fork contract, embedded in the payload).

    Device-path code on whatever jax platform the environment provides
    (XLA:CPU in the tier-1 gate); shapes are compile-warmed outside the
    timed region.  ONE JSON line, detail.facade; persisted to
    <ckpt-dir>/facade.json — the FACADE_r*.json contract.  Exit 1 when
    the coalesce ratio stays at 1, the speedup misses 50x, or a what-if
    query moves a live placement."""
    from karmada_tpu.estimator import wire
    from karmada_tpu.facade import FacadeService
    from karmada_tpu.facade import whatif as facade_whatif
    from karmada_tpu.facade.messages import WhatIfRequest
    from karmada_tpu.loadgen import (
        ServeSlice,
        ServiceModel,
        VirtualClock,
        get_scenario,
        warm_device_path,
    )
    from karmada_tpu.loadgen.driver import build_binding
    from karmada_tpu.models.cluster import Cluster
    from karmada_tpu.models.work import ResourceBinding
    from karmada_tpu.obs import events as obs_events

    n_callers = int(args.facade_callers)
    window = max(2, int(args.facade_window))
    sample = max(8, int(args.facade_serial_sample))
    scenario = get_scenario("steady")
    model = ServiceModel()
    clock = VirtualClock()
    plane = ServeSlice(scenario, clock, model, backend="device")
    _hb(f"facade: backend=device, {n_callers} callers, window={window}, "
        f"serial control sample={sample}")
    # compile-warm every pow2 binding-axis bucket a batch cut can pad to
    # (1..window): a fresh shape inside the timed region would bill a
    # jit compile to whichever leg hit it first
    warm_device_path(plane)
    clusters = plane.store.list(Cluster.KIND)
    sched = plane.scheduler
    k = 1
    while k <= window:
        warm = [facade_whatif.synthesize_binding(wire.AssignReplicasRequest(
            namespace="facade-bench", name=f"warm-{k}-{i}", replicas=1,
            resource_request={"cpu": "100m"}, divided=True))
            for i in range(k)]
        sched.solve_batch(warm, clusters, detached=True)
        k *= 2

    def req(i: int) -> wire.AssignReplicasRequest:
        # 100m per caller: a FULL window of hypothetical bindings must
        # schedule against the fleet snapshot (each batch solves
        # detached against the same snapshot, so it's one window's
        # demand that has to fit, not the whole caller population's)
        return wire.AssignReplicasRequest(
            namespace="facade-bench", name=f"caller-{i}", replicas=1,
            resource_request={"cpu": "100m"}, divided=True)

    # the documented perf-leg pattern (obs/events.disarm): both legs
    # price the solve path, not per-call ledger writes — and both legs
    # skip them equally, so the ratio is unchanged either way
    ledger_was_armed = obs_events.armed()
    obs_events.disarm()
    # collector pauses out of the timed region: a facade call allocates
    # ~40 containers, so gen2 fires every ~1700 calls and full-scans the
    # whole heap (the jax module graph) for ~80ms — a ~60us/call tax
    # that the 8192-call coalesced leg samples fully but a 64-call
    # serial control almost never does.  Freezing the warm heap and
    # disabling collection for both legs prices the SERVICE, not the
    # collector, and removes the sampling asymmetry between the legs.
    import gc
    gc.collect()
    gc.freeze()
    gc.disable()
    try:
        # -- serial control: one dispatch per call ----------------------------
        control = FacadeService(sched, plane.store, batch_window=1,
                                batch_deadline_s=0.001)
        try:
            control.assign(req(0))  # path-warm outside the timed region
            t0 = time.perf_counter()
            for i in range(sample):
                resp = control.assign(req(i))
                assert resp.outcome == "scheduled", resp.message
            serial_elapsed = time.perf_counter() - t0
            control_state = control.state_payload()
        finally:
            control.close()
        serial_per_call = serial_elapsed / sample

        # -- coalesced leg: a window of calls in flight, batch former ---------
        # deadline scales with the window: admitting a full window takes
        # ~10us/call on the main thread, and a deadline shorter than the
        # fill time makes the former cut PARTIAL batches — pricing extra
        # fixed dispatch costs that the window was chosen to amortize
        svc = FacadeService(sched, plane.store, batch_window=window,
                            batch_deadline_s=max(0.05, window * 200e-6))
        try:
            # warm burst: first full-window cut outside the timed region
            for h in [svc.assign_async(req(i)) for i in range(window)]:
                h.result()
            base = svc.state_payload()
            t0 = time.perf_counter()
            handles = [svc.assign_async(req(i)) for i in range(n_callers)]
            results = [h.result() for h in handles]
            batched_elapsed = time.perf_counter() - t0
            assert all(r.outcome == "scheduled" for r in results)
            state = svc.state_payload()
        finally:
            svc.close()
    finally:
        gc.enable()
        gc.unfreeze()
        if ledger_was_armed:
            obs_events.arm()
    batched_per_call = batched_elapsed / n_callers
    calls = state["calls"] - base["calls"]
    batches = state["batches"] - base["batches"]
    coalesce_ratio = round(calls / batches, 2) if batches else 0.0
    speedup = (round(serial_per_call / batched_per_call, 1)
               if batched_per_call > 0 else 0.0)
    _hb(f"facade: {calls} calls in {batches} batches "
        f"(coalesce {coalesce_ratio}x), per-call "
        f"{serial_per_call * 1e3:.2f}ms serial vs "
        f"{batched_per_call * 1e3:.3f}ms coalesced = {speedup}x")

    # -- what-if isolation proof on LIVE placements ---------------------------
    for i in range(32):
        plane.store.create(build_binding(f"facade-live-{i}", replicas=2,
                                         divided=True))
    for _ in range(200):
        if plane.runtime.tick() == 0 and all(
                rb.spec.clusters
                for rb in plane.store.list(ResourceBinding.KIND)
                if rb.metadata.name.startswith("facade-live-")):
            break

    def placements():
        return {
            (rb.metadata.namespace, rb.metadata.name): tuple(
                sorted((t.name, t.replicas) for t in rb.spec.clusters))
            for rb in plane.store.list(ResourceBinding.KIND)}

    before = placements()
    assert any(before.values()), "live bindings never scheduled"
    whatif_runs = {}
    for query in ("placement", "cluster-loss", "headroom"):
        resp = facade_whatif.run_query(sched, plane.store, WhatIfRequest(
            query=query, replicas=4, resource_request={"cpu": "500m"}))
        whatif_runs[query] = resp.to_json()
    whatif_isolated = placements() == before
    _hb(f"facade: what-if burst isolated={whatif_isolated} "
        f"(headroom {whatif_runs['headroom']['result']['max_replicas']} "
        "replicas)")

    payload = {
        "backend": "device",
        "callers": n_callers,
        "batch_window": window,
        "serial_sample": sample,
        "serial_per_call_s": round(serial_per_call, 6),
        "batched_per_call_s": round(batched_per_call, 6),
        "speedup_x": speedup,
        "calls": calls,
        "batches": batches,
        "coalesce_ratio": coalesce_ratio,
        "control": control_state,
        "service": state,
        "whatif": whatif_runs,
        "whatif_isolated": whatif_isolated,
    }
    os.makedirs(args.ckpt_dir, exist_ok=True)
    out_path = os.path.join(args.ckpt_dir, "facade.json")
    with open(out_path, "w") as f:
        json.dump(payload, f, indent=2)
    ok = coalesce_ratio > 1 and speedup >= 50 and whatif_isolated
    print(json.dumps({
        "metric": f"facade coalescing: {n_callers} callers, "
                  f"{batches} batched dispatches "
                  f"(coalesce {coalesce_ratio}x) vs serial per-call",
        "value": speedup,
        "unit": "x speedup",
        "vs_baseline": 0,
        "detail": {"facade": payload, "facade_path": out_path},
    }))
    return 0 if ok else 1


def _rebalance_parity_items(rng: random.Random, n: int, names):
    """A device-routed rebalance workload for the re-place parity leg:
    Duplicated / dynamic-weight Divided / Aggregated placements (no
    spread constraints or host rows — the carry chain's own territory),
    each with a previous assignment so the re-solve exercises the
    Steady/Fresh modes the descheduler reuses."""
    placements = []
    for _ in range(4):
        placements.append(Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED)))
    for _ in range(4):
        placements.append(Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_WEIGHTED,
            weight_preference=ClusterPreferences(
                dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS))))
    for _ in range(4):
        placements.append(Placement(replica_scheduling=ReplicaSchedulingStrategy(
            replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
            replica_division_preference=REPLICA_DIVISION_AGGREGATED)))
    items = build_bindings(rng, n, placements)
    return build_rebalance_items(rng, items, names)


def _serial_rebalance_control(items, clusters):
    """The reference semantics the batched re-solve must reproduce
    bit-exactly: one binding at a time, each seeing the previous ones'
    consumption as the positive delta over its prior assignment (the
    same rule the wave accumulator implements — tests/test_contention.py
    pins the equivalence; this is its bench-side control)."""
    import copy

    clusters = copy.deepcopy(clusters)
    cal = serial.make_cal_available([GeneralEstimator()])
    by_name = {c.metadata.name: c for c in clusters}
    results = []
    for spec, st in items:
        try:
            want = serial.schedule(spec, st, clusters, cal)
        except Exception as e:  # noqa: BLE001 — outcome object, like the queue
            results.append(e)
            continue
        results.append(want)
        prev = {tc.name: tc.replicas for tc in spec.clusters}
        req = spec.replica_requirements.resource_request
        for tc in want:
            delta = max(tc.replicas - prev.get(tc.name, 0), 0)
            if delta == 0:
                continue
            s = by_name[tc.name].status.resource_summary
            alloc = s.allocated
            alloc["cpu"] = Quantity.from_milli(
                alloc.get("cpu", Quantity(0)).milli
                + delta * req["cpu"].milli)
            alloc["memory"] = Quantity.from_units(
                alloc.get("memory", Quantity(0)).value()
                + delta * req["memory"].value())
            alloc["pods"] = Quantity.from_units(
                alloc.get("pods", Quantity(0)).value() + delta)
    return results


def run_rebalance(args) -> int:
    """bench --rebalance: the rebalance-plane acceptance payload
    (REBALANCE_r*.json contract), two legs:

    1. the compressed `hotspot` soak with the rebalance plane armed —
       skewed arrivals pack the hot clusters, capacity churn overcommits
       them, and the plane must drain them back inside the overcommit
       threshold through paced graceful evictions with ZERO conservation
       violations (safety auditor embedded in the payload);
    2. re-place parity — the drained set re-solved through the pipelined
       executor with the device-side carry chain (chunked, waves == chunk
       so the accounting is fully sequential) against the serial
       rebalance control, asserted bit-identical.

    Exit 1 on any violation, non-convergence, or parity mismatch."""
    from karmada_tpu.loadgen import (
        LoadDriver, ServeSlice, ServiceModel, VirtualClock, get_scenario,
        warm_device_path,
    )
    from karmada_tpu.scheduler import pipeline as sched_pipeline

    scenario = get_scenario("hotspot")
    _hb("rebalance soak (hotspot): fixed service model, backend=device "
        "(XLA:CPU off-hardware), rebalance plane + graceful eviction armed")
    model = ServiceModel()  # fixed, like --chaos: determinism over throughput
    clock = VirtualClock()
    plane = ServeSlice(scenario, clock, model, backend="device")
    warm_device_path(plane)
    driver = LoadDriver(plane, scenario, clock=clock, model=model,
                        seed=args.soak_seed)
    arm_telemetry()
    try:
        payload = driver.run()
    finally:
        disarm_telemetry()
    payload["backend"] = "device"
    reb = payload.get("rebalance") or {}
    last = reb.get("last") or {}
    audit = payload.get("safety_audit") or {}
    violations = list(audit.get("violations", []))
    thr = (reb.get("config") or {}).get("overcommit_threshold_milli", 1000)
    over_after = {
        name: row["over_milli"] for name, row in
        (last.get("clusters") or {}).items()
        if row["over_milli"] > thr and row["capacity"] > 0}
    if over_after:
        violations.append({"kind": "not-drained", "clusters": over_after})
    if not last.get("converged"):
        violations.append({"kind": "not-converged"})
    if not reb.get("evictions"):
        violations.append({"kind": "no-drains",
                           "detail": "the hotspot never triggered a drain"})
    _hb(f"soak done: evictions={reb.get('evictions')} "
        f"peak_over={reb.get('peak_over_milli')} "
        f"conservation_violations={reb.get('conservation_violations')}")

    # -- leg 2: re-place parity vs the serial rebalance control -------------
    rng = random.Random(0x5EB)
    clusters = build_fleet(rng, 16)
    names = [c.metadata.name for c in clusters]
    reb_items = _rebalance_parity_items(rng, 256, names)
    chunk = 64
    _hb(f"re-place parity: {len(reb_items)} rebalance re-solves, "
        f"pipelined chunk={chunk} carry=True vs serial control")
    estimator = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    t0 = time.perf_counter()
    res = sched_pipeline.run_pipeline(
        reb_items, cindex, estimator, chunk=chunk, waves=chunk,
        cache=tensors.EncoderCache(), carry=True, carry_spread=True)
    batched_s = time.perf_counter() - t0
    batched = _targets_of(res.results)
    control = _serial_rebalance_control(reb_items, clusters)
    want = _targets_of(dict(enumerate(control)))
    mismatches = [i for i in range(len(reb_items))
                  if batched.get(i, want.get(i)) != want[i]]
    if mismatches:
        violations.append({
            "kind": "replace-parity",
            "detail": f"{len(mismatches)} re-solve(s) diverged from the "
                      "serial rebalance control",
            "first": mismatches[:8]})
    parity = {
        "bindings": len(reb_items),
        "chunk": chunk,
        "device_rows": len(res.results),
        "mismatches": len(mismatches),
        "bit_identical": not mismatches,
        "batched_bindings_per_s": round(len(reb_items) / batched_s, 1),
    }
    _hb(f"parity done: {parity['device_rows']} device rows, "
        f"{parity['mismatches']} mismatch(es)")

    out = {
        "version": 1,
        "scenario": scenario.name,
        "seed": args.soak_seed,
        "drain": {
            "threshold_milli": thr,
            "peak_over_milli": reb.get("peak_over_milli"),
            "final": last.get("clusters"),
            "evictions": reb.get("evictions"),
            "cycles": reb.get("cycles"),
            "converged": bool(last.get("converged")),
            "conservation_violations": reb.get("conservation_violations"),
        },
        "replace_parity": parity,
        "violations": violations,
        "slo": payload.get("slo"),
        "soak": payload,
    }
    os.makedirs(args.ckpt_dir, exist_ok=True)
    out_path = os.path.join(args.ckpt_dir, "rebalance_hotspot.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({
        "metric": "rebalance hotspot: violations "
                  f"({reb.get('evictions')} drains, parity over "
                  f"{len(reb_items)} re-solves)",
        "value": len(violations),
        "unit": "violations",
        "vs_baseline": 0,
        "detail": {"rebalance": out, "rebalance_path": out_path},
    }))
    return 0 if not violations else 1


def build_megafleet(rng: random.Random, n_clusters: int, n_regions: int):
    """The million-user fleet shape: `n_clusters` clusters round-robined
    into `n_regions` regions, and ONE shared Divided+DynamicWeight
    placement per region whose cluster affinity names exactly that
    region's clusters — per-tenant eligible sets a shortlist k covers
    (each tenant's traffic stays inside its region, the reference's own
    hierarchy: group selection before per-cluster division)."""
    clusters = build_fleet(rng, n_clusters)
    for i, c in enumerate(clusters):
        c.spec.region = f"r{i % n_regions}"
    by_region: Dict[str, List[str]] = {}
    for c in clusters:
        by_region.setdefault(c.spec.region, []).append(c.metadata.name)
    placements = []
    for r in sorted(by_region, key=lambda s: int(s[1:])):
        placements.append(Placement(
            cluster_affinity=ClusterAffinity(cluster_names=by_region[r]),
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=REPLICA_DIVISION_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS)),
        ))
    return clusters, placements


def build_mega_bindings(rng: random.Random, n: int, placements,
                        block: int) -> list:
    """`n` bindings whose placement group advances every `block`
    bindings (tenant-clustered arrival order: a chunk's bindings mostly
    share a region, which is what keeps the candidate union narrow —
    real queues batch per tenant burst, not round-robin across every
    tenant)."""
    # shared requirement objects (9 classes): a million specs must not
    # carry a million Quantity dicts, and the encoder's request-class
    # dedup hits the same Q rows either way
    reqs = [
        ReplicaRequirements(resource_request={
            "cpu": Quantity.from_milli(cpu),
            "memory": Quantity.from_units(mem),
        })
        for cpu in (100, 250, 500) for mem in (1, 2, 4)
    ]
    status = ResourceBindingStatus()
    items = []
    for b in range(n):
        pl = placements[(b // max(block, 1)) % len(placements)]
        spec = ResourceBindingSpec(
            resource=ObjectReference(
                api_version=GVK[0], kind=GVK[1], namespace=f"ns-{b % 64}",
                name=f"mega-{b}", uid=f"uid-mega-{b}"),
            # ~2 replicas/binding keeps a 1M-binding fleet inside the
            # 10k clusters' ~1.8M free-pod envelope (demand ~ capacity;
            # the tail that does not fit prices as real contention)
            replicas=rng.choice([1, 2, 3]),
            replica_requirements=reqs[rng.randrange(len(reqs))],
            placement=pl,
        )
        items.append((spec, status))
    return items


def _targets_of(res) -> list:
    if isinstance(res, Exception):
        return []
    return [(t.name, t.replicas) for t in res]


def run_megafleet(args) -> int:
    """bench --megafleet: the hierarchical two-tier solve acceptance
    payload (ops/shortlist).  Runs the device-path code on XLA:CPU
    (forced before backend init — never blocks on the tunnel, like
    --delta).  Four legs:

      real      N bindings x C clusters through the pipelined executor
                with the shortlist armed — throughput, per-chunk cell
                work (B*C' solved vs B*C dense-equivalent), fallback and
                widen counters, peak device/host memory (obs/devprof).
      recall    a sampled slice solved BOTH ways: shortlisted placements
                asserted bit-exact against the dense control, and
                shortlist recall (dense-chosen clusters present in the
                candidate set) reported.
      soak      the loadgen `megafleet` scenario compressed on the
                virtual clock (device backend, shortlist armed end to
                end through serve's real queue/batch machinery) — zero
                fallbacks asserted.
      project   the 1M x 10k virtual-clock extrapolation from the real
                leg's measured per-binding cost.

    Exit 1 on parity mismatch, recall < 0.999, cell-work reduction
    < 50x, or any shortlist fallback in the soak."""
    import resource

    force_cpu_fallback()
    from karmada_tpu.obs import devprof
    from karmada_tpu.ops import shortlist as sl_mod

    rng = random.Random(20260804)
    n_clusters = args.megafleet_clusters
    n_regions = args.megafleet_regions
    n_bindings = args.megafleet_bindings
    k = args.megafleet_k
    chunk = args.chunk
    _hb(f"megafleet: building {n_clusters} clusters in {n_regions} "
        f"regions, {n_bindings} bindings")
    clusters, placements = build_megafleet(rng, n_clusters, n_regions)
    items = build_mega_bindings(rng, n_bindings, placements, block=chunk)
    cindex = tensors.ClusterIndex.build(clusters)
    estimator = GeneralEstimator()
    cfg = sl_mod.ShortlistConfig(k=k, min_cells=0)

    # -- recall + parity leg (sampled dense comparison slice) ---------------
    sample_n = min(args.megafleet_sample, n_bindings)
    sample = items[:sample_n]
    _hb(f"megafleet: dense-vs-shortlist parity over {sample_n} sampled "
        "bindings")
    from karmada_tpu.scheduler import pipeline as sched_pipeline

    def run_slice(shortlist_cfg):
        cache = tensors.EncoderCache()
        return sched_pipeline.run_pipeline(
            sample, cindex, estimator, chunk=chunk, waves=args.waves,
            cache=cache, carry=True, carry_spread=True,
            shortlist=shortlist_cfg, diagnose=False)

    dense_res = run_slice(None)
    sl_res = run_slice(cfg)
    mismatches = sum(
        1 for i in dense_res.results
        if _targets_of(dense_res.results[i]) != _targets_of(
            sl_res.results.get(i))
        or isinstance(dense_res.results[i], Exception)
        != isinstance(sl_res.results.get(i), Exception))
    # recall: dense-chosen clusters present in the tier-1 candidate set
    batch = tensors.encode_batch(sample, cindex, estimator)
    cand_sets = sl_mod.binding_candidates(batch, k)
    names_idx = {n: i for i, n in enumerate(cindex.names)}
    chosen = hit = 0
    for i, res in dense_res.results.items():
        cset = cand_sets[i]
        for name, _rep in _targets_of(res):
            chosen += 1
            hit += 1 if names_idx[name] in cset else 0
    recall = (hit / chosen) if chosen else 1.0

    # -- real throughput leg ------------------------------------------------
    cells0 = {t: 0.0 for t in ("solve", "dense_equiv")}
    for t in cells0:
        cells0[t] = sl_mod.SHORTLIST_CELLS.value(tier=t)
    disp0 = sl_mod.SHORTLIST_DISPATCHES.value()
    fb0 = sl_mod.SHORTLIST_FALLBACKS.total()
    w0 = sl_mod.SHORTLIST_WIDENINGS.value()
    _hb(f"megafleet: real leg ({n_bindings} bindings x {n_clusters} "
        f"clusters, chunk {chunk}, k={k})")
    elapsed, solve_s, scheduled, chunk_lat, chunk_wall, failures = (
        run_megafleet_pipeline(items, cindex, estimator, chunk,
                               args.waves, cfg))
    devprof.refresh_memory_gauges()
    cells_solve = sl_mod.SHORTLIST_CELLS.value(tier="solve") - cells0["solve"]
    cells_dense = (sl_mod.SHORTLIST_CELLS.value(tier="dense_equiv")
                   - cells0["dense_equiv"])
    reduction = (cells_dense / cells_solve) if cells_solve else 0.0
    # processed = every binding the two-tier solve priced (unschedulable
    # rows pay the full pipeline too); scheduled is the success subset
    bps = n_bindings / elapsed if elapsed > 0 else 0.0
    real = {
        "bindings": n_bindings, "clusters": n_clusters,
        "regions": n_regions, "k": k, "chunk": chunk,
        "scheduled": scheduled, "failures": failures,
        "wall_s": round(elapsed, 3),
        "processed_per_s": round(bps, 1),
        "scheduled_per_s": round(scheduled / elapsed, 1)
        if elapsed > 0 else 0.0,
        "chunks": len(chunk_lat),
        "chunk_own_mean_s": round(float(np.mean(chunk_lat)), 4)
        if chunk_lat else None,
        "cells_solved": int(cells_solve),
        "cells_dense_equiv": int(cells_dense),
        "cell_reduction_x": round(reduction, 1),
        "shortlist_dispatches": int(
            sl_mod.SHORTLIST_DISPATCHES.value() - disp0),
        "shortlist_fallbacks": int(sl_mod.SHORTLIST_FALLBACKS.total() - fb0),
        "widenings": int(sl_mod.SHORTLIST_WIDENINGS.value() - w0),
    }

    # -- compressed virtual-clock soak (serve path end to end) --------------
    from karmada_tpu.loadgen import (
        LoadDriver, ServeSlice, VirtualClock, get_scenario,
    )

    scenario = get_scenario("megafleet")
    _hb(f"megafleet: compressed {scenario.name} soak (device backend, "
        f"shortlist k={scenario.shortlist_k})")
    model = ServiceModel_for_soak()
    clock = VirtualClock()
    plane = ServeSlice(scenario, clock, model, backend="device")
    fb_soak0 = sl_mod.SHORTLIST_FALLBACKS.total()
    disp_soak0 = sl_mod.SHORTLIST_DISPATCHES.value()
    driver = LoadDriver(plane, scenario, clock=clock, model=model,
                        seed=args.soak_seed)
    soak_payload = driver.run()
    soak = {
        "injected": soak_payload.get("injected"),
        "scheduled": soak_payload.get("scheduled"),
        "shortlist_dispatches": int(
            sl_mod.SHORTLIST_DISPATCHES.value() - disp_soak0),
        "shortlist_fallbacks": int(
            sl_mod.SHORTLIST_FALLBACKS.total() - fb_soak0),
        "virtual_duration_s": soak_payload.get("duration_s"),
    }

    # -- 1M x 10k virtual-clock projection ----------------------------------
    target_b, target_c = 1_000_000, max(n_clusters, 10_000)
    per_binding_s = (elapsed / n_bindings) if n_bindings else float("inf")
    projected_s = target_b * per_binding_s
    project = {
        "target_bindings": target_b, "target_clusters": target_c,
        "per_binding_ms": round(per_binding_s * 1e3, 4),
        "projected_wall_s": round(projected_s, 1),
        "within_one_hour": bool(projected_s < 3600),
        "dense_cells": target_b * target_c,
        "two_tier_cells": target_b * k,
        "cell_reduction_x": round(target_c / k, 1),
    }

    ru = resource.getrusage(resource.RUSAGE_SELF)
    payload_detail = {
        "real": real,
        "recall": {"sample": sample_n, "parity_mismatches": mismatches,
                   "recall": round(recall, 6), "chosen": chosen},
        "soak": soak,
        "project": project,
        "memory": {
            "devices": devprof.memory_stats_payload(),
            "peak_rss_bytes": int(ru.ru_maxrss) * 1024,
        },
        "shortlist_state": sl_mod.state_payload(),
    }
    ok = (mismatches == 0 and recall >= 0.999 and reduction >= 50.0
          and soak["shortlist_fallbacks"] == 0
          and soak["shortlist_dispatches"] > 0)
    os.makedirs(args.ckpt_dir, exist_ok=True)
    out_path = os.path.join(args.ckpt_dir, "megafleet.json")
    with open(out_path, "w") as f:
        json.dump(payload_detail, f, indent=2)
    print(json.dumps({
        "metric": f"megafleet two-tier solve ({n_bindings}x{n_clusters}, "
                  f"k={k}): cell work vs dense",
        "value": round(reduction, 1),
        "unit": "x reduction",
        "vs_baseline": round(reduction, 1),
        "detail": {**payload_detail, "megafleet_path": out_path,
                   "ok": ok},
    }))
    return 0 if ok else 1


def ServiceModel_for_soak():
    """Fixed service model for the megafleet soak — determinism over
    calibrated throughput, exactly like --chaos / --rebalance."""
    from karmada_tpu.loadgen import ServiceModel

    return ServiceModel()


def run_megafleet_pipeline(items, cindex, estimator, chunk, waves, cfg):
    """run_batched's aggregates with the shortlist armed (collect off —
    a megafleet run must not hold a million result lists)."""
    from karmada_tpu.scheduler import pipeline as sched_pipeline

    scheduled = 0
    failures: Dict[str, int] = {}
    solve_s = 0.0
    chunk_lat, chunk_wall = [], []

    def on_chunk(st) -> None:
        nonlocal scheduled, solve_s
        scheduled += st.n_ok
        for kk, v in st.failures.items():
            failures[kk] = failures.get(kk, 0) + v
        chunk_lat.append(st.own_s)
        chunk_wall.append(st.wall_s)
        solve_s += st.solve_s
        _hb(f"megafleet chunk {st.index + 1} finalized ({st.n} bindings)")

    cache = tensors.EncoderCache()
    t0 = time.perf_counter()
    sched_pipeline.run_pipeline(
        items, cindex, estimator, chunk=chunk, waves=waves, cache=cache,
        carry=True, carry_spread=True, on_chunk=on_chunk,
        collect=False, diagnose=False, shortlist=cfg)
    return (time.perf_counter() - t0, solve_s, scheduled, chunk_lat,
            chunk_wall, failures)


def run_incremental(args) -> int:
    """bench --incremental: the dirty-set steady state at megafleet
    scale (ops/dirty + scheduler/incremental).  Same 1M x 10k fleet
    shape as --megafleet, but RESIDENT: adopt once (full solve), then
    watch-driven cycles that re-solve only the dirty sub-batch against
    the carried capacity ledger.  Legs:

      adopt     full solve + write-back + self-churn settle + cluster
                status catch-up (the whole ledger retires) — untimed.
      steady    `--incremental-cycles` timed cycles at
                `--incremental-churn` fraction (replica bumps + rv, the
                coalesced-deltas contract); p50/p99 wall, dirty rows,
                dispatch groups.
      capacity  a cluster status flap mid-stream (ledger lane retire on
                the hot path).
      audit     one final forced bit-exact dense-control audit — parity
                asserted in-run against the SAME pre-cycle ledger.

    Exit 1 on audit mismatch, any shortlist fallback (silent dense
    work), any chunk-dragged fallback row, or a steady-state speedup
    below 20x vs the committed MEGAFLEET_r01 full-cycle wall."""
    import resource

    force_cpu_fallback()
    from karmada_tpu.obs import devprof
    from karmada_tpu.ops import shortlist as sl_mod
    from karmada_tpu.resident import ResidentState
    from karmada_tpu.resident.deltas import CycleDeltas
    from karmada_tpu.scheduler.incremental import IncrementalSolver

    rng = random.Random(20260807)
    n_clusters = args.incremental_clusters
    n_regions = args.incremental_regions
    n_bindings = args.incremental_bindings
    chunk = args.chunk
    _hb(f"incremental: building {n_clusters} clusters in {n_regions} "
        f"regions, {n_bindings} bindings")
    clusters, placements = build_megafleet(rng, n_clusters, n_regions)
    # steady-fit fleet: triple the pod envelope so every dynamic row
    # converges (assigned == replicas).  The steady-state claim is about
    # CHURN cost — rows the fleet cannot fit are permanently capacity-
    # sensitive and re-price every cycle by design (they are the
    # contention story, measured in --megafleet)
    for c in clusters:
        q = c.status.resource_summary.allocatable["pods"]
        c.status.resource_summary.allocatable["pods"] = (
            Quantity.from_units(int(q.value()) * 3))
    specs = build_mega_bindings(rng, n_bindings, placements, block=chunk)
    bindings = [
        ResourceBinding(
            metadata=ObjectMeta(namespace=spec.resource.namespace,
                                name=spec.resource.name,
                                resource_version=1),
            spec=spec, status=status)
        for spec, status in specs
    ]
    del specs

    state = ResidentState(audit_interval=0)
    cfg = sl_mod.ShortlistConfig(k=args.incremental_k, min_cells=0)
    solver = IncrementalSolver(state, GeneralEstimator(), chunk=chunk,
                               audit_every=args.audit_every,
                               shortlist=cfg)
    fb0 = sl_mod.SHORTLIST_FALLBACKS.total()
    drag0 = sl_mod.SHORTLIST_FALLBACK_ROWS.value(kind="chunk_drag")
    needed0 = sl_mod.SHORTLIST_FALLBACK_ROWS.value(kind="needed")

    _hb("incremental: adopt (full solve)")
    rep = solver.adopt(clusters, bindings)
    adopt_s = rep.seconds
    _hb(f"incremental: adopt done in {adopt_s:.1f}s "
        f"({len(solver.results)} results); write-back + settle")
    written = solver.write_back()
    t0 = time.perf_counter()
    settle = solver.cycle(clusters, bindings, CycleDeltas())
    settle_s = time.perf_counter() - t0
    solver.write_back()
    _hb(f"incremental: settle cycle {settle_s:.1f}s "
        f"(dirty {settle.dirty} of {settle.total})")
    # cluster status catch-up: every member reports fresh capacity, so
    # the entire adopt-era ledger retires (reported availability now
    # embeds it)
    for c in clusters:
        c.metadata.resource_version += 1
    catchup = solver.cycle(clusters, bindings, CycleDeltas())
    solver.write_back()
    ledger_live = sum(int(np.count_nonzero(a))
                      for a in solver.ledger.milli.values())
    _hb(f"incremental: status catch-up (dirty {catchup.dirty}, "
        f"{ledger_live} live ledger lanes)")

    # -- steady-state churn cycles -----------------------------------------
    churned = max(1, int(n_bindings * args.incremental_churn))
    walls, dirties, group_counts = [], [], []
    for cyc in range(args.incremental_cycles):
        touched = []
        for pos in rng.sample(range(n_bindings), churned):
            rb = bindings[pos]
            rb.spec.replicas = max(
                1, rb.spec.replicas + rng.choice((-1, 1)))
            rb.metadata.resource_version += 1
            touched.append((rb.namespace, rb.name))
        deltas = CycleDeltas(bindings_touched=touched)
        t0 = time.perf_counter()
        rep = solver.cycle(clusters, bindings, deltas)
        wall = time.perf_counter() - t0
        solver.write_back()
        assert rep.mode == "incremental", rep
        walls.append(wall)
        dirties.append(rep.dirty)
        group_counts.append(len(rep.groups))
        _hb(f"incremental: steady cycle {cyc + 1}/"
            f"{args.incremental_cycles}: {wall:.3f}s, dirty {rep.dirty}, "
            f"{len(rep.groups)} dispatch group(s)")

    # -- capacity churn leg -------------------------------------------------
    flapped = rng.sample(clusters, 2)
    for c in flapped:
        q = c.status.resource_summary.allocatable["pods"]
        c.status.resource_summary.allocatable["pods"] = (
            Quantity.from_units(max(8, int(q.value()) - 16)))
        c.metadata.resource_version += 1
    t0 = time.perf_counter()
    cap_rep = solver.cycle(clusters, bindings, CycleDeltas())
    cap_wall = time.perf_counter() - t0
    solver.write_back()
    _hb(f"incremental: capacity flap cycle {cap_wall:.3f}s "
        f"(dirty {cap_rep.dirty})")

    # -- final forced audit (the bit-exact gate, in-run) --------------------
    _hb("incremental: forced dense-control audit")
    t0 = time.perf_counter()
    audit_rep = solver.cycle(clusters, bindings, CycleDeltas(),
                             force_audit=True)
    audit_wall = time.perf_counter() - t0
    devprof.refresh_memory_gauges()

    p50 = float(np.percentile(walls, 50))
    p99 = float(np.percentile(walls, 99))
    baseline_s = 140.59  # MEGAFLEET_r01 real-leg full-cycle wall
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "MEGAFLEET_r01.json")) as f:
            baseline_s = float(
                json.load(f)["detail"]["real"]["wall_s"])
    except (OSError, KeyError, ValueError):
        pass
    speedup = baseline_s / p50 if p50 > 0 else 0.0
    fallbacks = int(sl_mod.SHORTLIST_FALLBACKS.total() - fb0)
    chunk_drag = int(
        sl_mod.SHORTLIST_FALLBACK_ROWS.value(kind="chunk_drag") - drag0)
    needed_rows = int(
        sl_mod.SHORTLIST_FALLBACK_ROWS.value(kind="needed") - needed0)

    ru = resource.getrusage(resource.RUSAGE_SELF)
    payload = {
        "fleet": {"bindings": n_bindings, "clusters": n_clusters,
                  "regions": n_regions, "k": args.incremental_k,
                  "chunk": chunk},
        "adopt": {"wall_s": round(adopt_s, 3), "written_back": written,
                  "settle_wall_s": round(settle_s, 3),
                  "settle_dirty": settle.dirty},
        "catchup": {"dirty": catchup.dirty,
                    "ledger_live_lanes": ledger_live},
        "steady": {
            "churn_frac": args.incremental_churn,
            "churned_per_cycle": churned,
            "cycles": args.incremental_cycles,
            "wall_p50_s": round(p50, 4),
            "wall_p99_s": round(p99, 4),
            "walls_s": [round(w, 4) for w in walls],
            "dirty_rows": dirties,
            "dirty_rows_mean": round(float(np.mean(dirties)), 1),
            "dispatch_groups": group_counts,
        },
        "capacity_churn": {"flapped": len(flapped),
                           "wall_s": round(cap_wall, 4),
                           "dirty": cap_rep.dirty},
        "audit": {"outcome": audit_rep.audit_outcome,
                  "wall_s": round(audit_wall, 3),
                  "rows": audit_rep.total,
                  "audit_every": args.audit_every},
        "fallbacks": {"shortlist_chunks": fallbacks,
                      "rows_needed": needed_rows,
                      "rows_chunk_drag": chunk_drag},
        "speedup": {"baseline_full_cycle_s": baseline_s,
                    "steady_p50_s": round(p50, 4),
                    "speedup_x": round(speedup, 1)},
        "memory": {
            "devices": devprof.memory_stats_payload(),
            "peak_rss_bytes": int(ru.ru_maxrss) * 1024,
        },
    }
    ok = (audit_rep.audit_outcome == "ok" and fallbacks == 0
          and chunk_drag == 0 and speedup >= 20.0)
    root = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(root, "MEGAFLEET_r02.json")
    summary = {
        "metric": f"incremental steady-state cycle ({n_bindings}x"
                  f"{n_clusters}, {args.incremental_churn:.2%} churn) "
                  "vs full re-solve",
        "value": round(speedup, 1),
        "unit": "x speedup",
        "vs_baseline": round(speedup, 1),
        "detail": {**payload, "incremental": {
            "adopt_s": round(adopt_s, 3),
            "steady_p50_s": round(p50, 4),
            "steady_p99_s": round(p99, 4),
            "dirty_rows_mean": round(float(np.mean(dirties)), 1),
            "speedup_x": round(speedup, 1),
            "audit_outcome": audit_rep.audit_outcome,
            "fallbacks": fallbacks,
            "chunk_drag_rows": chunk_drag,
        }, "megafleet_r02_path": out_path, "ok": ok},
    }
    with open(out_path, "w") as f:
        json.dump(summary, f, indent=2)
    os.makedirs(args.ckpt_dir, exist_ok=True)
    with open(os.path.join(args.ckpt_dir, "megafleet_incremental.json"),
              "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary))
    return 0 if ok else 1


def _synth_coo(batch, err_every: int = 97):
    """A realistic decode workload without paying a 5000-cluster XLA:CPU
    solve: per ROUTE_DEVICE row, Duplicated placements emit one entry per
    feasible cluster (exactly what the kernel's ``n * sel`` broadcast
    extracts — full-fleet placements make WIDE rows), divided strategies
    emit up to 3 Webster seats; every ``err_every``-th row gets a
    FIT_ERROR / UNSCHEDULABLE status.  Ascending row-major int32 planes —
    solver._compact_of's d2h contract."""
    nb, C, nC = batch.n_bindings, batch.C, batch.n_clusters
    strat = batch.pl_strategy[batch.placement_id]
    idx_l, val_l = [], []
    status = np.zeros(batch.B, np.int32)
    for b in range(nb):
        if batch.route[b] != tensors.ROUTE_DEVICE:
            continue
        if err_every and b % err_every == 0:
            status[b] = (tensors.STATUS_FIT_ERROR if b % (2 * err_every)
                         else tensors.STATUS_UNSCHEDULABLE)
            continue
        pid = int(batch.placement_id[b])
        rep = int(batch.replicas[b])
        if strat[b] == 0:  # Duplicated: one entry per feasible cluster
            for c in np.nonzero(batch.pl_mask[pid][:nC])[0]:
                idx_l.append(b * C + int(c))
                val_l.append(0 if batch.non_workload[b] else rep)
        else:
            seats = sorted({(b * 7 + j * 13) % nC for j in range(1 + b % 3)})
            for j, c in enumerate(seats):
                idx_l.append(b * C + c)
                val_l.append(max(rep - j, 0))
    max_nnz = len(idx_l) + 64
    idx = np.full(max_nnz, -1, np.int32)
    val = np.zeros(max_nnz, np.int32)
    idx[:len(idx_l)] = idx_l
    val[:len(val_l)] = val_l
    return idx, val, status, len(idx_l)


def _decode_equal(a, b) -> bool:
    """Bit-exact decode parity: same exception class on error slots, same
    (name, replicas) target lists (dataclass ==, order included)."""
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if isinstance(x, Exception) or isinstance(y, Exception):
            if type(x) is not type(y):
                return False
        elif x != y:
            return False
    return True


def _measure_decode(args) -> dict:
    """The host-budget half of the coldstart payload: warm encode + warm
    decode ms/chunk at (chunk x clusters), native vs the pre-PR fast path
    vs the pure-Python parity control, parity asserted bit-exact."""
    import statistics

    from karmada_tpu import native as native_mod

    rng = random.Random(0)
    chunk = min(args.chunk, 4096)
    clusters = build_fleet(rng, args.clusters)
    placements = build_placements(rng, [c.name for c in clusters])
    items = build_bindings(rng, 2 * chunk, placements)
    estimator = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)
    cache = tensors.EncoderCache()
    batch = tensors.encode_batch(items[:chunk], cindex, estimator,
                                 cache=cache)
    tensors.encode_batch(items[chunk:2 * chunk], cindex, estimator,
                         cache=cache)
    # warm (sig-hit) encode: the steady-state per-chunk cost
    enc_ts = []
    for _ in range(9):
        t0 = time.perf_counter()
        tensors.encode_batch(items[:chunk], cindex, estimator, cache=cache)
        enc_ts.append((time.perf_counter() - t0) * 1e3)
    idx, val, status, entries = _synth_coo(batch)

    def timed(n=11):
        ts = []
        out = None
        for _ in range(n):
            t0 = time.perf_counter()
            out = tensors.decode_compact(batch, idx, val, status, items=None)
            ts.append((time.perf_counter() - t0) * 1e3)
        return out, {"mean_ms": round(statistics.mean(ts), 2),
                     "median_ms": round(statistics.median(ts), 2),
                     "min_ms": round(min(ts), 2)}

    native_ok = native_mod.load_decode_fast() is not None
    out_native, t_native = timed()
    # pre-PR control: numpy split + the narrow-row helper in encode_fast.c
    saved = (native_mod._dec_mod, native_mod._dec_error)  # noqa: SLF001
    native_mod._dec_mod, native_mod._dec_error = None, "disabled (control)"  # noqa: SLF001
    out_prev, t_prev = timed()
    # pure-Python parity control (the behavior-defining fallback)
    saved_enc = (native_mod._enc_mod, native_mod._enc_error)  # noqa: SLF001
    native_mod._enc_mod, native_mod._enc_error = None, "disabled (control)"  # noqa: SLF001
    out_py, t_py = timed(5)
    native_mod._dec_mod, native_mod._dec_error = saved  # noqa: SLF001
    native_mod._enc_mod, native_mod._enc_error = saved_enc  # noqa: SLF001

    parity = (_decode_equal(out_native, out_prev)
              and _decode_equal(out_native, out_py))
    dec_ms = t_native["median_ms"]
    enc_ms = statistics.median(enc_ts)
    r05_baseline_ms = 46.0  # PERF_NOTES r05: warm decode ms/chunk @4096x5000
    return {
        "chunk": chunk, "clusters": args.clusters, "coo_entries": entries,
        "native_extension": native_ok,
        "decode_native": t_native,
        "decode_prev_fastpath": t_prev,
        "decode_pure_python": t_py,
        "decode_parity_bit_exact": parity,
        "speedup_vs_prev": round(t_prev["median_ms"] / dec_ms, 2),
        "speedup_vs_python": round(t_py["median_ms"] / dec_ms, 2),
        "r05_baseline_ms_per_chunk": r05_baseline_ms,
        "speedup_vs_r05_baseline": round(r05_baseline_ms / dec_ms, 2),
        "encode_warm_ms": round(enc_ms, 2),
        "host_budget_bps": round(chunk / ((enc_ms + dec_ms) / 1e3), 1),
    }


def run_coldstart_child(args) -> int:
    """--coldstart-child (spawned by run_coldstart, one per PROCESS): arm
    the persistent compile cache at the given dir, AOT-warm the requested
    pow2 shapes x all jit variants, and print one JSON line with the
    warmup seconds + the persistent-cache hit/miss counters."""
    force_cpu_fallback()
    from karmada_tpu.ops import aotcache

    # min_compile_time 0: even trivial compiles persist, so a warm second
    # process can assert literally ZERO cache misses
    aotcache.enable(args.coldstart_cache, min_compile_time_s=0.0)
    rng = random.Random(0)
    clusters = build_fleet(rng, args.coldstart_clusters)
    shapes = tuple(int(s) for s in args.coldstart_shapes.split(",") if s)
    t0 = time.perf_counter()
    res = aotcache.warm_executables(clusters, GeneralEstimator(),
                                    shapes=shapes,
                                    variants=aotcache.ALL_VARIANTS,
                                    waves=args.waves)
    warmup_s = time.perf_counter() - t0
    hits, misses = aotcache.counters()
    totals = res.get("_totals", {})
    print(json.dumps({"warmup_s": round(warmup_s, 3),
                      # the XLA-compile share — what r02's compile_warmup_s
                      # measured and what the persistent cache eliminates;
                      # lower_s (tracing) is paid by every process
                      "compile_s": totals.get("compile_s"),
                      "lower_s": totals.get("lower_s"),
                      "hits": hits, "misses": misses,
                      "per_executable": {k: v for k, v in res.items()
                                         if k != "_totals"}}))
    return 0


def run_coldstart(args) -> int:
    """bench --coldstart: the AOT executable plane's acceptance payload.

    (a) Two-process cold start: spawn the SAME warmup workload twice in
    fresh processes sharing one tmp cache dir — the first pays real XLA
    compiles (cache misses), the second must deserialize everything
    (zero misses, warmup well under the first's).  (b) Warm host budget:
    encode + decode ms/chunk at (--chunk x --clusters) with the native
    decode vs its controls, parity asserted bit-exact.  ONE JSON line
    (detail.coldstart); persisted to <ckpt-dir>/coldstart.json — the
    COLDSTART_r*.json contract."""
    import shutil
    import subprocess

    _hb(f"coldstart: measuring warm host budget "
        f"({min(args.chunk, 4096)}x{args.clusters})")
    decode_payload = _measure_decode(args)
    _hb(f"decode native {decode_payload['decode_native']['median_ms']}ms "
        f"vs prev {decode_payload['decode_prev_fastpath']['median_ms']}ms; "
        f"host budget {decode_payload['host_budget_bps']} bindings/s")

    cache_dir = os.path.join(args.ckpt_dir, "coldstart_cache")
    shutil.rmtree(cache_dir, ignore_errors=True)
    os.makedirs(cache_dir, exist_ok=True)
    child_argv = [
        sys.executable, os.path.abspath(__file__), "--coldstart-child",
        "--coldstart-cache", cache_dir,
        "--coldstart-clusters", str(args.coldstart_clusters),
        "--coldstart-shapes", args.coldstart_shapes,
        "--waves", str(args.waves),
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    runs = []
    for which in ("first", "second"):
        _hb(f"coldstart: {which} process warming "
            f"shapes {args.coldstart_shapes} (cache {cache_dir})")
        r = subprocess.run(child_argv, capture_output=True, text=True,
                           env=env, timeout=1800)
        line = _last_json_line((r.stdout or "").splitlines())
        if r.returncode != 0 or not line:
            print(json.dumps({
                "metric": "coldstart failed (child)", "value": 0,
                "unit": "ratio", "vs_baseline": 0,
                "detail": {"which": which, "rc": r.returncode,
                           "stderr_tail": (r.stderr or "")[-800:]}}))
            return 1
        runs.append(json.loads(line))
        _hb(f"coldstart {which}: warmup {runs[-1]['warmup_s']}s "
            f"hits={runs[-1]['hits']} misses={runs[-1]['misses']}")
    first, second = runs
    ratio = (second["warmup_s"] / first["warmup_s"]
             if first["warmup_s"] > 0 else 0.0)
    # the acceptance ratio: XLA-compile seconds only — tracing (lower_s)
    # is paid by every process whether or not a cache exists, exactly
    # like the first jit call's tracing; r02's ~100s compile_warmup_s
    # was the compile share
    compile_ratio = (second["compile_s"] / first["compile_s"]
                     if (first.get("compile_s") or 0) > 0 else 0.0)
    payload = {
        "first": first, "second": second,
        "warm_ratio": round(ratio, 4),
        "compile_warm_ratio": round(compile_ratio, 4),
        "second_misses": second["misses"],
        "cache_dir": cache_dir,
        "shapes": args.coldstart_shapes,
        "variants": "plain,explain,carry,donated",
        "decode": decode_payload,
    }
    os.makedirs(args.ckpt_dir, exist_ok=True)
    out_path = os.path.join(args.ckpt_dir, "coldstart.json")
    with open(out_path, "w") as f:
        json.dump(payload, f, indent=2)
    print(json.dumps({
        "metric": "coldstart: second-process compile warmup fraction "
                  f"(shapes {args.coldstart_shapes} x 4 variants, "
                  "shared persistent cache)",
        "value": round(compile_ratio, 4),
        "unit": "ratio",
        "vs_baseline": 0,
        "detail": {"coldstart": payload, "coldstart_path": out_path},
    }))
    ok = (second["misses"] == 0 and compile_ratio < 0.1
          and decode_payload["decode_parity_bit_exact"])
    return 0 if ok else 1


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--bindings", type=int, default=100_000)
    ap.add_argument("--clusters", type=int, default=5_000)
    ap.add_argument("--chunk", type=int, default=4096)
    ap.add_argument("--serial-sample", type=int, default=64)
    ap.add_argument("--quick", action="store_true", help="small smoke config")
    ap.add_argument("--force-cpu", action="store_true",
                    help="skip the device probe and run on host CPU")
    ap.add_argument("--metrics", action="store_true",
                    help="dump the metrics registry to stderr after the run")
    ap.add_argument("--probe-timeout", type=float, default=330.0)
    ap.add_argument("--waves", type=int, default=8,
                    help="capacity-contention waves per solver chunk")
    ap.add_argument("--carry", action="store_true",
                    help="thread consumed-capacity accumulators chunk to "
                         "chunk (sequential-equivalent accounting at chunk "
                         "granularity; serializes the pipeline and "
                         "disables checkpoint resume)")
    ap.add_argument("--soak", default=None, metavar="SCENARIO",
                    help="sustained-traffic soak mode (karmada_tpu/"
                         "loadgen): calibrate this host's real per-"
                         "binding solve cost, run the named scenario in "
                         "compressed virtual time against the serve "
                         "slice's admission/batch-formation machinery, "
                         "and emit the SOAK payload (p50/p95/p99 "
                         "schedule latency + queue dwell from flight-"
                         "recorder spans, shed/admission counts, "
                         "starvation ages).  Host-only: never touches "
                         "the device tunnel.  `karmadactl loadgen` "
                         "lists scenarios")
    ap.add_argument("--soak-backend", choices=["serial", "native"],
                    default="serial",
                    help="scheduler backend the soak drives (and "
                         "calibrates against)")
    ap.add_argument("--chaos", default=None, metavar="SCENARIO",
                    help="chaos soak mode (karmada_tpu/chaos + loadgen): "
                         "run a chaos-enabled scenario in compressed "
                         "virtual time against the device backend with "
                         "the resident plane, death guard, and estimator "
                         "harness armed; emits the fault ledger + safety "
                         "auditor payload (CHAOS_r*.json contract).  "
                         "Exit 1 on any conservation violation.")
    ap.add_argument("--slo", action="store_true",
                    help="with --soak: assert the telemetry acceptance "
                         "gate — the SLO verdict must be computed from "
                         ">= 20 ring samples, the sampler must cost "
                         "< 1%% of a cycle, and sampling must trigger "
                         "zero jit compiles (the verdict itself is "
                         "always embedded, flag or not)")
    ap.add_argument("--soak-seed", type=int, default=0,
                    help="deterministic arrival-process seed")
    ap.add_argument("--facade", action="store_true",
                    help="facade acceptance mode (karmada_tpu/facade): "
                         "server-side batch coalescing measured against "
                         "a serial per-call control (one detached solve "
                         "per caller), plus the what-if isolation proof "
                         "on live placements; emits the FACADE_r*.json "
                         "payload.  Device-path code on whatever jax "
                         "platform the environment provides (XLA:CPU in "
                         "the gate), never blocks on the tunnel.  Exit 1 "
                         "when the coalesce ratio stays at 1, the "
                         "speedup misses 50x, or a what-if query moves "
                         "a live placement")
    ap.add_argument("--facade-callers", type=int, default=8192,
                    help="in-flight AssignReplicas calls in the "
                         "coalesced leg")
    ap.add_argument("--facade-window", type=int, default=1024,
                    help="facade batch window for the coalesced leg "
                         "(1024 amortizes the fixed dispatch cost to "
                         "~2.5us/call; the solver's marginal per-binding "
                         "cost IMPROVES with batch size on XLA:CPU)")
    ap.add_argument("--facade-serial-sample", type=int, default=64,
                    help="sequential calls timed through the window=1 "
                         "serial control")
    ap.add_argument("--rebalance", action="store_true",
                    help="rebalance acceptance mode (karmada_tpu/"
                         "rebalance + loadgen): run the hotspot scenario "
                         "in compressed virtual time with the rebalance "
                         "plane armed (device backend on whatever jax "
                         "platform the environment provides), then assert "
                         "re-place parity of the carry-chain re-solve vs "
                         "the serial rebalance control; emits the "
                         "REBALANCE_r*.json payload.  Exit 1 on any "
                         "conservation violation, non-convergence, or "
                         "parity mismatch.")
    ap.add_argument("--megafleet", action="store_true",
                    help="megafleet acceptance mode (ops/shortlist): the "
                         "hierarchical two-tier solve at fleet scale — "
                         "real throughput + cell-work reduction with the "
                         "shortlist armed, sampled dense-parity + recall, "
                         "the compressed loadgen megafleet scenario on "
                         "the virtual clock (device backend end to end), "
                         "and the 1Mx10k projection; emits "
                         "MEGAFLEET_r*.json.  XLA:CPU, never blocks on "
                         "the tunnel.  Exit 1 on parity/recall/"
                         "reduction/fallback gate misses")
    ap.add_argument("--megafleet-bindings", type=int, default=16384,
                    help="real-leg binding count (the 1M claim rides the "
                         "virtual-clock projection from this measured leg)")
    ap.add_argument("--megafleet-clusters", type=int, default=10000)
    ap.add_argument("--megafleet-regions", type=int, default=200)
    ap.add_argument("--megafleet-k", type=int, default=64,
                    help="tier-1 candidate lanes per binding")
    ap.add_argument("--megafleet-sample", type=int, default=2048,
                    help="dense-comparison slice for parity + recall")
    ap.add_argument("--incremental", action="store_true",
                    help="incremental acceptance mode (ops/dirty + "
                         "scheduler/incremental): the dirty-set steady "
                         "state at megafleet scale — adopt once, then "
                         "watch-driven cycles re-solving only the dirty "
                         "sub-batch against the carried capacity ledger; "
                         "steady p50/p99 at the configured churn, a "
                         "cluster-status capacity flap, and a final "
                         "forced bit-exact dense-control audit; emits "
                         "MEGAFLEET_r02.json.  XLA:CPU, never blocks on "
                         "the tunnel.  Exit 1 on audit mismatch, any "
                         "shortlist fallback, any chunk-dragged fallback "
                         "row, or steady speedup < 20x vs MEGAFLEET_r01")
    ap.add_argument("--incremental-bindings", type=int, default=1_000_000)
    ap.add_argument("--incremental-clusters", type=int, default=10_000)
    ap.add_argument("--incremental-regions", type=int, default=200)
    ap.add_argument("--incremental-k", type=int, default=64,
                    help="tier-1 candidate lanes per binding (the "
                         "incremental cycles keep the two-tier shortlist "
                         "armed end to end)")
    ap.add_argument("--incremental-cycles", type=int, default=8,
                    help="timed steady-state churn cycles")
    ap.add_argument("--incremental-churn", type=float, default=0.001,
                    help="per-cycle churned-binding fraction (replica "
                         "bumps + rv, the coalesced-deltas contract)")
    ap.add_argument("--audit-every", type=int, default=16,
                    help="incremental audit cadence (every Nth cycle "
                         "runs the full dense control bit-exact; 0 "
                         "disables — the final audit is always forced)")
    ap.add_argument("--mesh", nargs="?", const="auto", default=None,
                    help="mesh bench mode: run the SAME workload through "
                         "the pipelined executor single-device and sharded "
                         "over a (bindings, clusters) device mesh "
                         "(ops/meshing), verify bit-identical results, and "
                         "report topology + 1-vs-N timing in one JSON "
                         "payload.  Value is BxC (e.g. 2x4) or 'auto' "
                         "(factor --mesh-devices).  Always runs on virtual "
                         "CPU devices — never blocks on the tunnel.")
    ap.add_argument("--delta", action="store_true",
                    help="delta bench mode: steady-state scheduling-cycle "
                         "timing with the resident-state plane (karmada_"
                         "tpu/resident) at the --delta-churn fractions vs "
                         "today's full re-encode path, on the same "
                         "workload (--bindings x --clusters).  Runs every "
                         "resident leg twice — fused device-gather "
                         "(ops/resident_gather) ON and OFF — with a "
                         "per-stage host-budget breakdown (encode-"
                         "assembly / gather / dispatch / d2h / decode ms "
                         "per cycle) and a warm all-hits re-place leg.  "
                         "Re-encoded-row exactness, fused-vs-host "
                         "placement parity, zero binding-axis h2d on the "
                         "fused path, and the plane's bit-exact audit "
                         "are all asserted.  Always runs the device-path "
                         "code on XLA:CPU — never blocks on the tunnel.")
    ap.add_argument("--delta-churn", default="0.01,0.10",
                    help="comma-separated per-cycle churn fractions the "
                         "delta bench times (default: 1%% and 10%%)")
    ap.add_argument("--coldstart", action="store_true",
                    help="coldstart mode (ops/aotcache acceptance): "
                         "two-process AOT compile-cache measurement "
                         "(fresh processes sharing one cache dir; the "
                         "second must show zero misses) plus the warm "
                         "host-budget encode/decode ms/chunk with the "
                         "native decoder vs its parity controls.  "
                         "Host-only, never blocks on the tunnel")
    ap.add_argument("--coldstart-child", action="store_true",
                    help=argparse.SUPPRESS)  # spawned by --coldstart
    ap.add_argument("--coldstart-cache", default="",
                    help=argparse.SUPPRESS)
    ap.add_argument("--coldstart-clusters", type=int, default=64,
                    help="cluster-axis size for the two-process compile "
                         "measurement (small: the point is compile time, "
                         "not solve scale)")
    ap.add_argument("--coldstart-shapes", default="8,32",
                    help="comma-separated binding-axis shapes the "
                         "coldstart children AOT-warm (pow2-padded)")
    ap.add_argument("--mesh-devices", type=int, default=8,
                    help="virtual CPU devices to pin for --mesh auto")
    ap.add_argument("--mesh-bindings", type=int, default=256,
                    help="--mesh workload size (kept small: the virtual "
                         "CPU mesh emulates collectives by thread "
                         "rendezvous on shared host cores)")
    ap.add_argument("--mesh-clusters", type=int, default=64)
    ap.add_argument("--mesh-chunk", type=int, default=64)
    ap.add_argument("--inner", action="store_true",
                    help="run the bench in this process (no watchdog parent)")
    ap.add_argument("--no-progress-timeout", type=float, default=600.0,
                    help="watchdog: kill the device attempt after this many "
                         "seconds with neither output nor CPU activity, "
                         "then CPU-fallback")
    ap.add_argument("--ckpt-dir", default=default_ckpt_dir(),
                    help="per-chunk checkpoint + cached-controls directory")
    ap.add_argument("--fresh", action="store_true",
                    help="ignore chunk checkpoints, cached serial controls "
                         "and the persisted TPU result; measure everything")
    ap.add_argument("--prefer-cached", action="store_true",
                    help="with --force-cpu: report a persisted on-TPU "
                         "measurement instead of running on CPU (set by "
                         "the watchdog's fallback re-exec; an explicit "
                         "--force-cpu run stays a CPU run)")
    ap.add_argument("--no-cpu-fallback", action="store_true",
                    help="exit nonzero instead of re-running on host CPU "
                         "when the device attempt hangs or dies (watcher "
                         "mode: checkpoints keep the finished chunks)")
    ap.add_argument("--fallback-backend", choices=["native", "xla-cpu"],
                    default="native",
                    help="what to measure when no accelerator answers: the "
                         "native C++ backend (the production serve reroute, "
                         "~13x faster than the XLA program on host CPU) or "
                         "the XLA:CPU batched path (exercises the device-"
                         "path code end to end on host)")
    ap.add_argument("--xla-cpu-sample", type=int, default=8192,
                    help="bindings for the XLA:CPU batched comparison "
                         "sample inside the native fallback (0 disables)")
    args = ap.parse_args()
    if args.quick:
        args.bindings, args.clusters, args.chunk = 2048, 256, 1024
        args.serial_sample = 32

    global _HB_ON
    if args.coldstart_child:
        raise SystemExit(run_coldstart_child(args))
    if args.coldstart:
        # coldstart mode is host-only and self-contained: children pin
        # JAX_PLATFORMS=cpu and the decode half never dispatches a solve —
        # same never-block guarantee as --soak / --delta / --mesh
        _HB_ON = True
        raise SystemExit(run_coldstart(args))
    if args.soak is not None:
        # soak mode is host-only and self-contained (virtual clock +
        # measured service model; serial/native backends): no device
        # probe, no watchdog parent — same never-block guarantee as
        # --mesh mode
        _HB_ON = True
        raise SystemExit(run_soak(args))
    if args.chaos is not None:
        # chaos mode is self-contained (virtual clock, fixed service
        # model, whatever jax platform the environment provides —
        # JAX_PLATFORMS=cpu in the tier-1 gate); the scheduler's own
        # mid-serve death guard bounds a hung device cycle, so no probe
        # and no watchdog parent
        _HB_ON = True
        raise SystemExit(run_chaos(args))
    if args.facade:
        # facade mode is self-contained: device-path code end to end on
        # whatever jax platform the environment provides (JAX_PLATFORMS=
        # cpu in the tier-1 gate), shapes compile-warmed before the
        # timed region — same never-block guarantee as --chaos
        _HB_ON = True
        raise SystemExit(run_facade(args))
    if args.rebalance:
        # rebalance mode is self-contained (virtual clock, fixed service
        # model, XLA:CPU off-hardware like --chaos): the drain loop and
        # the parity control never touch the device tunnel
        _HB_ON = True
        raise SystemExit(run_rebalance(args))
    if args.megafleet:
        # megafleet mode is self-contained: XLA:CPU forced before backend
        # init (the mode validates the two-tier solve, never the tunnel)
        _HB_ON = True
        raise SystemExit(run_megafleet(args))
    if args.incremental:
        # incremental mode is self-contained like --megafleet: XLA:CPU
        # forced before backend init, no probe, no watchdog parent
        _HB_ON = True
        raise SystemExit(run_incremental(args))
    if args.delta:
        # delta mode is host-only and self-contained: the resident plane's
        # device-path code runs byte-identical on XLA:CPU (forced before
        # backend init), so no probe and no watchdog parent — same
        # never-block guarantee as --mesh / --soak.
        _HB_ON = True
        raise SystemExit(run_delta_bench(args))
    if args.mesh is not None:
        # mesh mode is self-contained: virtual CPU devices only (the mode
        # validates mesh scaling, never the tunnel — same never-block
        # guarantee as __graft_entry__.dryrun_multichip), so no probe and
        # no watchdog parent.  "--mesh off"/"1x1" means NO mesh — the
        # regular bench, same vocabulary as serve --mesh.
        from karmada_tpu.ops import meshing as _meshing

        try:
            _shape = _meshing.parse_shape(args.mesh)
        except ValueError as e:
            print(json.dumps({"metric": "mesh bench failed (shape)",
                              "value": 0, "unit": "bindings/s",
                              "vs_baseline": 0,
                              "detail": {"error": str(e)}}))
            raise SystemExit(1)
        if _shape is not None:
            _HB_ON = True
            raise SystemExit(run_mesh_bench(args, _shape))
        args.mesh = None  # fall through to the regular bench

    if not args.inner and not args.force_cpu:
        argv = [a for a in sys.argv[1:]]  # replayed verbatim into the child
        raise SystemExit(run_with_watchdog(
            argv, args.no_progress_timeout,
            cpu_fallback=not args.no_cpu_fallback))
    _HB_ON = args.inner

    # backend bring-up: probe first (out of process), THEN point the
    # compile cache at the platform-appropriate dir — all before the first
    # in-process jit
    if args.force_cpu:
        probe = {"ok": False, "platform": None,
                 "attempts": [{"ok": False, "err": "--force-cpu"}]}
        force_cpu_fallback()
        platform = "cpu (forced)"
    else:
        probe = probe_backend(timeout_s=args.probe_timeout)
        if probe["ok"]:
            platform = probe["platform"]
        else:
            force_cpu_fallback()
            platform = "cpu (fallback: device probe failed)"
    on_tpu = probe["ok"] and "tpu" in str(platform).lower()
    # same accelerator vocabulary as serve's reroute policy: a live GPU run
    # is a real device measurement (just not the TPU headline), only a
    # CPU-or-dead probe degrades to the native fallback
    from karmada_tpu.utils.deviceprobe import ACCELERATOR_PLATFORMS

    on_accel = probe["ok"] and any(
        p in str(platform).lower() for p in ACCELERATOR_PLATFORMS)
    # accelerator executables target the chip, not the host: share their
    # cache across hosts; only XLA:CPU artifacts are host-feature-bound
    enable_persistent_compile_cache("accel" if on_accel else "cpu")
    _hb(f"probe done: platform={platform}")

    if (not on_tpu and not args.fresh
            and (not args.force_cpu or args.prefer_cached)):
        # no chip right now, but a completed on-chip measurement of this
        # exact config from earlier in the round is a better round result
        # than a CPU-fallback number — print it, clearly labelled
        cached = load_tpu_latest(args.ckpt_dir, args)
        if cached is not None:
            emit_cached_tpu(cached, why_no_live=str(
                probe["attempts"][-1].get("err", "probe failed")
                if probe.get("attempts") else "probe failed"))
            return
        if args.no_cpu_fallback and not args.force_cpu:
            print(json.dumps({"metric": "device probe failed "
                                        "(no-cpu-fallback)",
                              "value": 0, "unit": "bindings/s",
                              "vs_baseline": 0,
                              "detail": {"backend_probe": probe}}))
            raise SystemExit(3)

    rng = random.Random(0)
    clusters = build_fleet(rng, args.clusters)
    placements = build_placements(rng, [c.name for c in clusters])
    items = build_bindings(rng, args.bindings, placements)
    estimator = GeneralEstimator()
    cindex = tensors.ClusterIndex.build(clusters)

    if not on_accel and args.fallback_backend == "native":
        # no accelerator: measure what production would actually run here —
        # serve's device backend degrades to the native C++ pipeline, so
        # the fallback bench does too (XLA:CPU batched is measured as a
        # labelled comparison subsample inside)
        from karmada_tpu import native as native_mod

        if native_mod.available():
            try:
                run_native_fallback(args, rng, clusters, items, estimator,
                                    cindex, probe, platform)
                return
            except Exception as e:  # noqa: BLE001 — diagnostic trail
                import traceback

                print(json.dumps({
                    "metric": "bench failed (native fallback)", "value": 0,
                    "unit": "bindings/s", "vs_baseline": 0,
                    "detail": {"error": repr(e),
                               "trace_tail": traceback.format_exc()[-800:]},
                }))
                raise SystemExit(1)
        print("[bench] native toolchain unavailable; falling back to the "
              "XLA:CPU batched path", file=sys.stderr, flush=True)

    try:
        # resumable checkpoints: a relay drop mid-run costs one chunk
        # three hardware kinds: chunks measured on different hardware must
        # never fold into one aggregate on resume
        sig = config_sig(
            args, "tpu" if on_tpu else ("accel" if on_accel else "cpu"))
        sig_reb = sig + "-reb"  # the rebalance pass checkpoints separately
        chunks_path = os.path.join(args.ckpt_dir, "chunks.jsonl")
        if args.fresh or args.carry:
            # --fresh bypasses checkpoint READS (and retires this sig's
            # stale records via prune); newly measured chunks are still
            # recorded so an interrupted fresh run resumes correctly.
            # --carry cannot resume (a skipped chunk's consumption would
            # vanish from the accounting).
            ckpt_done, prior_elapsed = {}, 0.0
            reb_done, reb_prior = {}, 0.0
        else:
            ckpt_done, prior_elapsed = load_ckpt(chunks_path, sig)
            reb_done, reb_prior = load_ckpt(chunks_path, sig_reb)
        ckpt_log = (None if args.carry
                    else ChunkLog(chunks_path, sig, prune=args.fresh))
        n_chunks = (len(items) + args.chunk - 1) // args.chunk
        n_restored = sum(1 for ci in range(n_chunks) if ci in ckpt_done)
        n_reb_restored = sum(1 for ci in range(n_chunks) if ci in reb_done)
        _hb(f"checkpoint: {n_restored}/{n_chunks} forward + "
            f"{n_reb_restored}/{n_chunks} rebalance chunks restored"
            f" (+{prior_elapsed:.1f}s prior elapsed)")

        cache = tensors.EncoderCache()
        compile_s = 0.0
        if n_restored < n_chunks or n_reb_restored < n_chunks:
            # warmup: compile every chunk shape once (full + any tail shape)
            _hb("compile warmup starting")
            t_compile = time.perf_counter()
            # warmup must match the timed run's jit signatures: carry mode
            # compiles the with_used variants + the used0 operands
            run_batched(items[: min(args.chunk, len(items))], cindex,
                        estimator, args.chunk, cache, waves=args.waves,
                        carry=args.carry)
            tail = len(items) % args.chunk
            # the tail shape is needed by BOTH the forward and rebalance
            # passes — warm it unless both already checkpointed their tail
            if tail and ((n_chunks - 1) not in ckpt_done
                         or (n_chunks - 1) not in reb_done):
                run_batched(items[:tail], cindex, estimator, args.chunk,
                            cache, waves=args.waves, carry=args.carry)
            compile_s = time.perf_counter() - t_compile
            _hb(f"compile warmup done in {compile_s:.1f}s; timed run starting")

        if ckpt_log is not None:
            ckpt_log.reset_t0()
        # flight recorder (karmada_tpu/obs): armed for the timed passes
        # only (never the warmup) so the payload carries a per-stage
        # timeline — a throughput regression becomes attributable to
        # encode/dispatch/wait/d2h/decode, not just a total.  Span cost is
        # ~10 objects per multi-second chunk: noise next to device work.
        from karmada_tpu import obs
        from karmada_tpu.obs.export import latest_pipeline_timeline

        obs.TRACER.configure(capacity=4, slow_keep=2)
        # telemetry plane: ring sampled once per finalized chunk, SLO
        # verdict + sampler overhead embedded in the payload (so the
        # BENCH_r* contract carries the same verdict shape the soak and
        # serve paths render)
        telemetry_ring = arm_telemetry()
        (elapsed, solve_s, scheduled, chunk_lat, chunk_wall,
         failures) = run_batched(
            items, cindex, estimator, args.chunk, cache, waves=args.waves,
            ckpt_done=ckpt_done, ckpt_log=ckpt_log, carry=args.carry)
        stage_timeline = latest_pipeline_timeline(obs.TRACER.recorder)
        elapsed += prior_elapsed
        throughput = args.bindings / elapsed
        _hb(f"timed run done: {throughput:.1f} bindings/s")

        sc_early = None
        if on_tpu:
            # the tunnel can die ANY moment after the forward pass: persist
            # the completed on-chip measurement IMMEDIATELY (no host work
            # first), then enrich it with the serial-control speedup once
            # those (cached, host-CPU) numbers exist.  A later round-end
            # bench reports this even if the window never finishes.
            def forward_payload(sc) -> dict:
                speedup = (throughput / sc["serial_bps"]
                           if sc and sc["serial_bps"] > 0 else 0.0)
                return {
                    "metric": (f"scheduled bindings/sec, {args.bindings} "
                               f"bindings x {args.clusters} clusters "
                               "(end-to-end batched; forward pass only, "
                               "rebalance pending)"),
                    "value": round(throughput, 1),
                    "unit": "bindings/s",
                    "vs_baseline": round(speedup, 2),
                    "detail": {
                        "platform": platform, "partial": True,
                        "rebalance": "pending (window may have closed)",
                        "batched_elapsed_s": round(elapsed, 3),
                        "scheduled_ok": scheduled,
                        "failed_by_class": failures,
                        "p99_chunk_latency_s": round(
                            float(np.percentile(chunk_lat, 99)), 4)
                        if chunk_lat else None,
                        "serial_bindings_per_s": (
                            round(sc["serial_bps"], 2) if sc else None),
                        "serial_lang": (sc["serial_lang"] if sc
                                        else "pending"),
                        "chunk": args.chunk, "waves": args.waves,
                        "resumed_chunks": n_restored,
                        "stage_timeline": stage_timeline,
                    },
                }

            save_tpu_latest(args.ckpt_dir, args, forward_payload(None))
            _hb("partial on-TPU result persisted (forward pass)")
            sc_early = measure_serial_controls(args, items, clusters,
                                               estimator)
            save_tpu_latest(args.ckpt_dir, args, forward_payload(sc_early))

        # descheduler rebalance loop (BASELINE config 5, second half) over
        # ALL bindings: previously-scheduled bindings re-assigned with prev
        # seats (Steady scale-up/down + Fresh reschedule triggers),
        # chunked + checkpointed exactly like the forward pass
        _hb("rebalance pass starting")
        reb_items = build_rebalance_items(
            rng, items, [c.name for c in clusters])
        reb_log = (None if args.carry
                   else ChunkLog(chunks_path, sig_reb, prune=args.fresh))
        cache.reset_for_cycle()
        if reb_log is not None:
            reb_log.reset_t0()
        (reb_elapsed, _, reb_ok, reb_lat, _, reb_failures) = run_batched(
            reb_items, cindex, estimator, args.chunk, cache,
            waves=args.waves, ckpt_done=reb_done, ckpt_log=reb_log)
        reb_stage_timeline = latest_pipeline_timeline(obs.TRACER.recorder)
        obs.TRACER.disable()
        reb_elapsed += reb_prior
        rebalance_bps = (len(reb_items) / reb_elapsed
                         if reb_elapsed > 0 else 0.0)
        _hb(f"rebalance pass done: {rebalance_bps:.1f} bindings/s")

        # serial controls are platform-independent (pure host CPU): measure
        # once per config, cache, and never spend a chip window on them
        # (the TPU path already measured them for the partial persist —
        # reuse, --fresh included)
        sc = (sc_early if sc_early is not None
              else measure_serial_controls(args, items, clusters, estimator))
        serial_throughput = sc["serial_bps"]
        speedup = (throughput / serial_throughput
                   if serial_throughput > 0 else 0.0)

        # explain-plane cost probe (bounded slice; ~2 chunks x 5 runs):
        # armed overhead goes into the payload, and the disarmed re-run
        # asserts zero new jit compilations — the acceptance bar for
        # "the disarmed path is unchanged"
        _hb("explain overhead probe starting")
        explain_probe = measure_explain_overhead(
            items, cindex, estimator, min(args.chunk, 256), args.waves)
        _hb(f"explain overhead probe done: {explain_probe}")

        # telemetry verdict + sampler cost (obs/timeseries, obs/slo):
        # the SLO evaluator judges the chunk-sampled series, and the
        # overhead probe proves the sampler costs <1% of a mean chunk
        # with zero compiles / zero new metric families
        from karmada_tpu.obs import slo as obs_slo

        slo_verdict = None
        if len(telemetry_ring) >= 2:
            ev = obs_slo.active()
            if ev is not None:
                slo_verdict = ev.evaluate(telemetry_ring)
        telemetry_probe = measure_sampler_overhead(
            float(np.mean(chunk_lat)) if chunk_lat else None)
        telemetry_probe["ring_samples"] = len(telemetry_ring)
        disarm_telemetry()
        _hb(f"telemetry probe done: {telemetry_probe}")
    except Exception as e:  # noqa: BLE001 — leave a diagnostic trail, not a traceback
        import traceback

        print(json.dumps({
            "metric": "bench failed",
            "value": 0,
            "unit": "bindings/s",
            "vs_baseline": 0,
            "detail": {
                "platform": platform,
                "backend_probe": probe,
                "error": repr(e),
                "trace_tail": traceback.format_exc()[-800:],
            },
        }))
        raise SystemExit(1)

    # a benchmark whose hardware silently changed is not a benchmark:
    # non-TPU results are labelled in the headline metric and report 0
    # speedup so no dashboard can mistake them for the real thing
    if on_tpu:
        prefix = ""
    elif on_accel:
        prefix = f"NON-TPU ACCELERATOR ({platform}) "
    else:
        prefix = "CPU-FALLBACK (NOT TPU) "
    payload = {
        "metric": f"{prefix}scheduled bindings/sec, {args.bindings} bindings x "
                  f"{args.clusters} clusters (end-to-end batched)",
        "value": round(throughput, 1),
        "unit": "bindings/s",
        "vs_baseline": round(speedup, 2) if on_tpu else 0,
        "detail": {
            "platform": platform,
            "waves": args.waves,
            "carry": args.carry,
            # self-describing topology: how many devices this process saw
            # and whether a solver mesh was active (the probe's
            # device_count inside backend_probe covers the subprocess view)
            "device_topology": _device_topology(),
            "mesh": _mesh_info(),
            "cpu_fallback_speedup": None if on_tpu else round(speedup, 2),
            "backend_probe": probe,
            "batched_elapsed_s": round(elapsed, 3),
            "batched_solve_s": round(solve_s, 3),
            "compile_warmup_s": round(compile_s, 3),
            "p99_chunk_latency_s": round(
                float(np.percentile(chunk_lat, 99)), 4) if chunk_lat else None,
            "p99_chunk_wall_s": round(
                float(np.percentile(chunk_wall, 99)), 4) if chunk_wall else None,
            "scheduled_ok": scheduled,
            # honest within-batch contention accounting: bindings whose
            # demand exceeds the capacity earlier waves consumed fail
            # Unschedulable, exactly like sequential scheduling would
            "failed_by_class": failures,
            "rebalance_bindings_per_s": round(rebalance_bps, 1),
            "rebalance_ok": reb_ok,
            "rebalance_failed_by_class": reb_failures,
            "rebalance_p99_chunk_s": round(
                float(np.percentile(reb_lat, 99)), 4) if reb_lat else None,
            "rebalance_resumed_chunks": n_reb_restored,
            # per-stage timelines from the flight recorder (obs/export):
            # regressions attribute to a pipeline stage, not just a total
            "stage_timeline": stage_timeline,
            "rebalance_stage_timeline": reb_stage_timeline,
            # explain plane (serve --explain): armed-vs-disarmed cost on
            # this workload, plus proof the disarmed path stayed intact
            # (zero new jit compilations after an armed run)
            **explain_probe,
            # telemetry plane (serve --telemetry): SLO verdict over the
            # chunk-sampled ring + the sampler's measured price (the
            # BENCH_r08 contract)
            "slo": slo_verdict,
            **telemetry_probe,
            "serial_bindings_per_s": round(serial_throughput, 2),
            "serial_python_bindings_per_s": round(sc["py_serial_bps"], 2),
            "serial_sample": sc["native_sample"],
            "serial_python_sample": sc["py_sample"],
            "serial_cached": sc["cached"],
            "chunk": args.chunk,
            # resumability: >0 restored chunks means this aggregate spans
            # multiple sessions (relay drops between them); elapsed sums
            # each session's own span
            "resumed_chunks": n_restored,
            "sessions_elapsed_prior_s": round(prior_elapsed, 1),
            # honesty note (BASELINE.md): the >=50x north star is against a
            # serial *Go-equivalent* path.  The control here is the compiled
            # C++ serial scheduler (native/serial_solver.cc, golden-tested
            # against ops/serial.py) when the toolchain is available; the
            # Python port is reported alongside for continuity.
            "serial_lang": sc["serial_lang"],
        },
    }
    print(json.dumps(payload))
    if on_tpu:
        # --fresh bypasses cache READS only: a deliberate fresh on-chip
        # measurement is exactly the one worth persisting
        save_tpu_latest(args.ckpt_dir, args, payload)
    if args.metrics:
        from karmada_tpu.utils.metrics import REGISTRY

        print(REGISTRY.dump(), file=sys.stderr)


if __name__ == "__main__":
    main()
